"""DreamerV3 tests (reference rllib/algorithms/dreamerv3/): scalar
codecs, sequence replay, RSSM world-model fitting, stateful recurrent
acting through the env runner, and the end-to-end training step."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.rl.algorithms.dreamerv3 import (
    DreamerV3Config,
    DreamerV3Learner,
    DreamerV3ModuleSpec,
    symexp,
    symlog,
    twohot,
)
from ray_tpu.rl.episode import SingleAgentEpisode
from ray_tpu.rl.replay_buffer import SequenceReplayBuffer


def tiny_spec(**kw):
    defaults = dict(obs_dim=4, action_dim=2, discrete=True,
                    deter_dim=32, stoch_vars=4, stoch_classes=4,
                    units=32, mlp_layers=1, num_bins=41)
    defaults.update(kw)
    return DreamerV3ModuleSpec(**defaults)


# ---------------------------------------------------------------------------
# Codecs
# ---------------------------------------------------------------------------

def test_symlog_symexp_roundtrip():
    x = jnp.array([-1000.0, -1.0, 0.0, 0.5, 3000.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), np.asarray(x),
                               rtol=1e-4)


def test_twohot_is_distribution_and_invertible():
    bins = jnp.linspace(-20.0, 20.0, 41)
    y = jnp.array([[0.0, 1.5], [-3.0, 100.0]])
    t = twohot(symlog(y), bins)
    np.testing.assert_allclose(np.asarray(t.sum(-1)), 1.0, rtol=1e-5)
    rec = symexp(jnp.sum(t * bins, -1))
    np.testing.assert_allclose(np.asarray(rec), np.asarray(y), rtol=1e-3,
                               atol=1e-4)


# ---------------------------------------------------------------------------
# Sequence replay
# ---------------------------------------------------------------------------

def _episode(T, obs_dim=3, terminated=True, reward=1.0):
    ep = SingleAgentEpisode()
    ep.add_reset(np.zeros(obs_dim, np.float32))
    for t in range(T):
        ep.add_step(np.full(obs_dim, t + 1, np.float32), t % 2, reward,
                    terminated=(terminated and t == T - 1))
    return ep


def test_sequence_buffer_layout():
    buf = SequenceReplayBuffer(capacity=100, seed=0)
    added = buf.add_episodes([_episode(5)])
    # 5 transition rows + 1 terminal-obs row.
    assert added == 6 and len(buf) == 6
    s = buf._storage
    assert s["is_first"][0] == 1.0 and s["is_first"][1:6].sum() == 0
    assert s["cont"][5] == 0.0 and s["cont"][:5].min() == 1.0
    # Reward lands on the row of the obs it arrived with (shifted by 1).
    assert s["rewards"][0] == 0.0 and s["rewards"][1] == 1.0


def test_sequence_buffer_sample_shapes_and_window_reset():
    buf = SequenceReplayBuffer(capacity=1000, seed=0)
    buf.add_episodes([_episode(20) for _ in range(5)])
    batch = buf.sample(8, 10)
    assert batch["obs"].shape == (8, 10, 3)
    assert batch["actions"].shape == (8, 10, 1)
    for k in ("rewards", "is_first", "cont"):
        assert batch[k].shape == (8, 10)
    # Every window is usable standalone: row 0 always starts a segment.
    assert (batch["is_first"][:, 0] == 1.0).all()


def test_sequence_buffer_keeps_fragment_boundary_reward():
    """A non-done chunk's last reward must land in the stream (on the
    tail-obs row), not vanish at the fragment boundary."""
    buf = SequenceReplayBuffer(capacity=100, seed=0)
    chunk = _episode(3, terminated=False, reward=7.0)  # in-progress cut
    added = buf.add_episodes([chunk])
    assert added == 4  # 3 transition rows + tail-obs row
    s = buf._storage
    assert s["rewards"][3] == 7.0 and s["cont"][3] == 1.0
    # Tail row's zero action is never consumed: the next chunk opens a
    # new segment.
    buf.add_episodes([_episode(2)])
    assert s["is_first"][4] == 1.0


def test_sequence_buffer_truncation_bootstraps():
    buf = SequenceReplayBuffer(capacity=100, seed=0)
    ep = _episode(4, terminated=False)
    ep.truncated = True
    buf.add_episodes([ep])
    # Truncated final obs keeps cont=1 (bootstrap through it).
    assert buf._storage["cont"][4] == 1.0


# ---------------------------------------------------------------------------
# World model + learner
# ---------------------------------------------------------------------------

def _rand_batch(rng, B=3, T=6, obs_dim=4):
    batch = {
        "obs": rng.normal(size=(B, T, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, 2, size=(B, T, 1)).astype(np.float32),
        "rewards": rng.normal(size=(B, T)).astype(np.float32),
        "is_first": np.zeros((B, T), np.float32),
        "cont": np.ones((B, T), np.float32),
    }
    batch["is_first"][:, 0] = 1
    return batch


def test_world_model_fits_a_batch():
    lrn = DreamerV3Learner(tiny_spec(), horizon=4, seed=0)
    batch = _rand_batch(np.random.default_rng(0))
    losses = [lrn.update_from_batch(batch)["wm_loss"] for _ in range(25)]
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    m = lrn.metrics
    for k in ("actor_loss", "critic_loss", "entropy", "kl_dyn"):
        assert np.isfinite(m[k]), m


def test_learner_state_roundtrip():
    lrn = DreamerV3Learner(tiny_spec(), horizon=3, seed=0)
    lrn.update_from_batch(_rand_batch(np.random.default_rng(1)))
    state = lrn.get_state()
    lrn2 = DreamerV3Learner(tiny_spec(), horizon=3, seed=9)
    lrn2.set_state(state)
    a = jax.tree.leaves(lrn.params)
    b = jax.tree.leaves(lrn2.params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_continuous_action_head():
    spec = tiny_spec(discrete=False, action_dim=2)
    lrn = DreamerV3Learner(spec, horizon=3, seed=0)
    rng = np.random.default_rng(2)
    batch = _rand_batch(rng)
    batch["actions"] = rng.uniform(-1, 1, size=(3, 6, 2)).astype(np.float32)
    m = lrn.update_from_batch(batch)
    assert np.isfinite(m["total_loss"])
    state = spec.init_runner_state(2)
    a, logp, v, state2 = spec.act_stateful(
        lrn.params, state, jnp.zeros((2, 4)), jax.random.key(0),
        True, jnp.array([True, True]))
    assert a.shape == (2, 2) and np.abs(np.asarray(a)).max() <= 1.0


# ---------------------------------------------------------------------------
# Stateful acting
# ---------------------------------------------------------------------------

def test_act_stateful_resets_rows_on_is_first():
    spec = tiny_spec()
    params = spec.init(jax.random.key(0))
    state = spec.init_runner_state(2)
    obs = jnp.ones((2, 4))
    key = jax.random.key(1)
    # Step twice to build up nonzero state everywhere (after one step
    # from all-zero state only z is nonzero: h's GRU input was zero).
    _, _, _, state = spec.act_stateful(
        params, state, obs, key, True, jnp.array([True, True]))
    _, _, _, state = spec.act_stateful(
        params, state, obs, key, True, jnp.array([False, False]))
    assert float(jnp.abs(state["h"]).sum()) > 0
    # Resetting only row 0: its pre-step state contribution must vanish.
    _, _, _, s_reset = spec.act_stateful(
        params, state, obs, key, True, jnp.array([True, False]))
    _, _, _, s_zero = spec.act_stateful(
        params, spec.init_runner_state(2), obs, key, True,
        jnp.array([True, True]))
    np.testing.assert_allclose(np.asarray(s_reset["h"][0]),
                               np.asarray(s_zero["h"][0]), rtol=1e-5)
    assert not np.allclose(np.asarray(s_reset["h"][1]),
                           np.asarray(s_zero["h"][1]))


# ---------------------------------------------------------------------------
# End to end
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("env", ["CartPole-v1"])
def test_training_step_end_to_end(env):
    cfg = DreamerV3Config().environment(env)
    cfg.deter_dim = 32; cfg.stoch_vars = 4; cfg.stoch_classes = 4
    cfg.units = 32; cfg.mlp_layers = 1
    cfg.batch_size_B = 4; cfg.batch_length_T = 8; cfg.horizon = 4
    cfg.rollout_fragment_length = 24
    cfg.num_steps_sampled_before_learning_starts = 24
    cfg.training_ratio = 4.0
    algo = cfg.build()
    try:
        for _ in range(3):
            res = algo.train()
        assert res["replay_buffer_size"] > 0
        assert np.isfinite(res["wm_loss"])
        ev = algo.evaluate(num_episodes=1)
        assert ev["evaluation/num_episodes"] == 1
    finally:
        algo.stop()
