"""Task cancel + failure semantics (counterpart of
python/ray/tests/test_cancel.py, test_failure*.py)."""

import time

import pytest

import ray_tpu


def test_cancel_pending_task(ray_start_regular):
    @ray_tpu.remote
    def hog():
        time.sleep(30)

    @ray_tpu.remote
    def queued():
        return 1

    # fill all 4 CPUs, then queue one more and cancel it while pending
    hogs = [hog.remote() for _ in range(4)]
    time.sleep(0.5)
    victim = queued.remote()
    assert ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(victim, timeout=5)
    for h in hogs:
        ray_tpu.cancel(h, force=True)


def test_cancel_running_task_force(ray_start_regular):
    @ray_tpu.remote
    def forever():
        time.sleep(60)

    ref = forever.remote()
    time.sleep(0.8)  # ensure running
    assert ray_tpu.cancel(ref, force=True)
    with pytest.raises((ray_tpu.TaskCancelledError,
                        ray_tpu.WorkerCrashedError)):
        ray_tpu.get(ref, timeout=10)


def test_cancel_finished_task_noop(ray_start_regular):
    @ray_tpu.remote
    def quick():
        return 7

    ref = quick.remote()
    assert ray_tpu.get(ref, timeout=10) == 7
    assert not ray_tpu.cancel(ref)
    assert ray_tpu.get(ref) == 7  # value untouched


def test_task_retry_on_worker_crash(ray_start_regular):
    """A task that kills its worker on first attempt succeeds via retry."""
    import tempfile, os
    path = tempfile.mktemp()

    @ray_tpu.remote(max_retries=2)
    def die_once(p):
        import os
        if not os.path.exists(p):
            open(p, "w").close()
            os._exit(1)  # hard crash, no exception path
        return "survived"

    assert ray_tpu.get(die_once.remote(path), timeout=30) == "survived"
    os.unlink(path)


def test_no_retry_exhausted(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def always_dies():
        import os
        os._exit(1)

    with pytest.raises(ray_tpu.WorkerCrashedError):
        ray_tpu.get(always_dies.remote(), timeout=30)


def test_named_actor_name_reusable_after_kill(ray_start_regular):
    """Killing a named actor releases its name (reference frees names on
    death in GcsActorManager); a replacement with the same name must come
    up ALIVE, not die with 'name already taken'."""

    @ray_tpu.remote
    class Named:
        def who(self):
            import os
            return os.getpid()

    a = Named.options(name="reusable").remote()
    pid1 = ray_tpu.get(a.who.remote(), timeout=30)
    ray_tpu.kill(a)
    # name release happens when the GCS notices the worker die; poll
    from ray_tpu.core.runtime import get_runtime

    deadline = time.time() + 30
    while get_runtime().get_named_actor("reusable") is not None:
        assert time.time() < deadline, "name never released"
        time.sleep(0.05)
    b = Named.options(name="reusable").remote()
    pid2 = ray_tpu.get(b.who.remote(), timeout=30)
    assert pid1 != pid2
    ray_tpu.kill(b)


def test_actor_max_task_retries_resubmits_across_restart():
    """A method call delivered to an actor instance that dies mid-
    execution is resubmitted to the RESTARTED instance when the actor
    was created with max_task_retries (reference direct-actor-submitter
    retry-on-restart); without it the caller gets ActorDiedError."""
    import os
    import signal
    import time

    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(max_restarts=4, max_task_retries=4)
        class Slow:
            def pid_after(self, delay):
                import os as o
                import time as t

                t.sleep(delay)
                return o.getpid()

        a = Slow.options(num_cpus=0).remote()
        pid = ray_tpu.get(a.pid_after.remote(0), timeout=60)
        ref = a.pid_after.remote(1.0)   # in flight when the kill lands
        time.sleep(0.2)
        os.kill(pid, signal.SIGKILL)
        pid2 = ray_tpu.get(ref, timeout=120)  # retried on the restart
        assert pid2 != pid

        @ray_tpu.remote(max_restarts=4)  # NO task retries: old contract
        class Slow0:
            def pid_after(self, delay):
                import os as o
                import time as t

                t.sleep(delay)
                return o.getpid()

        b = Slow0.options(num_cpus=0).remote()
        pidb = ray_tpu.get(b.pid_after.remote(0), timeout=60)
        refb = b.pid_after.remote(1.0)
        time.sleep(0.2)
        os.kill(pidb, signal.SIGKILL)
        with pytest.raises(Exception) as ei:
            ray_tpu.get(refb, timeout=120)
        assert "died" in str(ei.value).lower()
    finally:
        ray_tpu.shutdown()
