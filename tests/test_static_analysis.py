"""raylint — the AST static-analysis suite (ray_tpu/analysis/).

Each pass is exercised against small fixture snippets/trees (positive,
negative, suppression, baseline), then the whole repo is run through
the real runner and must exit 0: the suite at head is conformant by
construction, and any regression (new swallow, undeclared wire op,
unregistered knob, blocking call on the receive path) fails here.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ray_tpu.analysis import core as acore  # noqa: E402
from ray_tpu.analysis import (  # noqa: E402
    blocking_pass,
    conformance_pass,
    except_pass,
    knob_pass,
)
from ray_tpu.analysis.__main__ import PASSES, main as raylint_main  # noqa: E402


# --------------------------------------------------------------------------
# exception hygiene
# --------------------------------------------------------------------------

def _swallow_lines(source):
    return [v.line for v in
            except_pass.scan_source(textwrap.dedent(source), "x.py")]


def test_swallow_detects_pass_continue_and_return_none():
    src = """
    def f(items):
        try:
            risky()
        except Exception:
            pass
        for it in items:
            try:
                risky(it)
            except ValueError:
                continue
        try:
            return risky()
        except OSError:
            return None
    """
    assert len(_swallow_lines(src)) == 3


def test_swallow_ignores_handlers_that_do_something():
    src = """
    import logging
    def f():
        try:
            risky()
        except Exception as exc:
            logging.warning("boom: %s", exc)
        try:
            risky()
        except Exception:
            cleanup()
            return None
        try:
            return risky()
        except OSError:
            return 0
    """
    assert _swallow_lines(src) == []


def test_swallow_suppression_and_baseline(tmp_path):
    code = textwrap.dedent("""
        def f():
            try:
                risky()
            except Exception:  # raylint: allow-swallow(best-effort probe)
                pass
            try:
                risky()
            except Exception:
                pass
    """)
    (tmp_path / "mod.py").write_text(code)
    violations = except_pass.scan_source(code, "mod.py")
    assert len(violations) == 2

    # Suppression silences only the annotated site.
    res = acore.apply_filters(str(tmp_path), violations, baseline={})
    assert len(res.suppressed) == 1 and res.suppressed[0][1] == \
        "best-effort probe"
    assert len(res.new) == 1

    # A reason-less allow-comment does NOT count.
    bad = code.replace("(best-effort probe)", "()")
    (tmp_path / "mod.py").write_text(bad)
    res = acore.apply_filters(
        str(tmp_path), except_pass.scan_source(bad, "mod.py"), baseline={})
    assert len(res.new) == 2

    # Baselining freezes the remaining site; a NEW swallow still fails.
    (tmp_path / "mod.py").write_text(code)
    baseline = acore.build_baseline(str(tmp_path), violations)
    res = acore.apply_filters(str(tmp_path), violations, baseline)
    assert len(res.new) == 0 and len(res.baselined) == 1

    grown = code + textwrap.dedent("""
        def g():
            try:
                risky()
            except ValueError:
                pass
    """)
    (tmp_path / "mod.py").write_text(grown)
    res = acore.apply_filters(
        str(tmp_path), except_pass.scan_source(grown, "mod.py"), baseline)
    assert len(res.new) == 1 and res.new[0].line > 8


def test_baseline_keys_survive_line_drift(tmp_path):
    code = "def f():\n    try:\n        g()\n    except OSError:\n" \
           "        pass\n"
    (tmp_path / "m.py").write_text(code)
    vs = except_pass.scan_source(code, "m.py")
    baseline = acore.build_baseline(str(tmp_path), vs)
    # Unrelated lines added ABOVE the frozen site: keys still match.
    shifted = "import os\nimport sys\n\n" + code
    (tmp_path / "m.py").write_text(shifted)
    vs2 = except_pass.scan_source(shifted, "m.py")
    res = acore.apply_filters(str(tmp_path), vs2, baseline)
    assert res.new == [] and len(res.baselined) == 1


# --------------------------------------------------------------------------
# knob registry
# --------------------------------------------------------------------------

def _knob_fixture(tmp_path, *, register=True, document=True, read=True):
    core_dir = tmp_path / "ray_tpu" / "core"
    core_dir.mkdir(parents=True)
    (tmp_path / "ray_tpu" / "__init__.py").write_text("")
    (core_dir / "__init__.py").write_text("")
    knob_decl = ('KNOBS = [Knob("RAY_TPU_DEMO_KNOB", "1", "bool", '
                 '"user", "demo")]\n') if register else "KNOBS = []\n"
    (core_dir / "knobs.py").write_text(knob_decl + "_CONFIG_DOCS = {}\n")
    (core_dir / "config.py").write_text("class Config:\n    pass\n")
    reader = ('import os\n'
              'V = os.environ.get("RAY_TPU_DEMO_KNOB", "1")\n'
              if read else "V = 1\n")
    (core_dir / "app.py").write_text(reader)
    table = ("# demo\n\n## Configuration knobs\n\n"
             "| `RAY_TPU_DEMO_KNOB` | `1` | bool | demo |\n")
    (tmp_path / "README.md").write_text(
        table if document else "# demo\n")
    return str(tmp_path)


def test_knob_pass_clean_fixture(tmp_path):
    root = _knob_fixture(tmp_path)
    assert knob_pass.run(root) == []


def test_knob_pass_unregistered(tmp_path):
    root = _knob_fixture(tmp_path, register=False, document=False)
    rules = {v.rule for v in knob_pass.run(root)}
    assert "knob-unregistered" in rules


def test_knob_pass_dead_and_undocumented(tmp_path):
    root = _knob_fixture(tmp_path, read=False, document=False)
    rules = {v.rule for v in knob_pass.run(root)}
    assert {"knob-dead", "knob-undocumented"} <= rules


def test_knob_pass_stale_doc(tmp_path):
    root = _knob_fixture(tmp_path)
    readme = tmp_path / "README.md"
    readme.write_text(readme.read_text() +
                      "| `RAY_TPU_GHOST_KNOB` | `x` | str | gone |\n")
    rules = {v.rule for v in knob_pass.run(root)}
    assert "knob-stale-doc" in rules


def test_knob_pass_config_drift(tmp_path):
    root = _knob_fixture(tmp_path)
    core_dir = tmp_path / "ray_tpu" / "core"
    (core_dir / "config.py").write_text(
        "class Config:\n    new_field: int = 3\n")
    rules = {v.rule for v in knob_pass.run(root)}
    assert "knob-config-drift" in rules


def test_knob_pass_default_drift(tmp_path):
    root = _knob_fixture(tmp_path)
    readme = tmp_path / "README.md"
    # Registry says "1", table claims "2" -> drift, anchored to the row.
    readme.write_text(readme.read_text().replace("| `1` |", "| `2` |"))
    vs = [v for v in knob_pass.run(root)
          if v.rule == "knob-default-drift"]
    assert len(vs) == 1
    assert vs[0].path == "README.md" and vs[0].line > 1
    # raylint: allow-knob(fixture knob name, not a real registry entry)
    assert "RAY_TPU_DEMO_KNOB" in vs[0].message


def test_knob_default_extraction_and_unset_normalization():
    import ast
    knobs_src = ('KNOBS = [Knob("RAY_TPU_A", "", "str", "user", "d"),\n'
                 '         Knob("RAY_TPU_B", "0.2", "float", "user", "d")]\n')
    defaults = knob_pass.extract_registry_defaults(ast.parse(knobs_src))
    # raylint: allow-knob(fixture knob names, not real registry entries)
    assert defaults == {"RAY_TPU_A": "", "RAY_TPU_B": "0.2"}
    cfg = knob_pass.extract_config_defaults(ast.parse(
        "class Config:\n    port: int = 0\n    flag: bool = True\n"
        "    weird: object = some_call()\n"))
    assert cfg == {"port": "0", "flag": "True"}
    # The rendered *(unset)* placeholder compares equal to "".
    table = ("## Configuration knobs\n\n"
             "| `RAY_TPU_A` | `*(unset)*` | str | d |\n"
             "| `RAY_TPU_B` | `0.2` | float | d |\n")
    cells = knob_pass.readme_table_defaults(table)
    assert cells["RAY_TPU_A"][0] == "" and cells["RAY_TPU_B"][0] == "0.2"


# --------------------------------------------------------------------------
# receive-loop / lock discipline
# --------------------------------------------------------------------------

def _blocking_violations(source, entries=("Server._handle",),
                         check_locks=False):
    import ast
    tree = ast.parse(textwrap.dedent(source))
    return blocking_pass.scan_module(
        tree, "mod.py", entry_patterns=entries, check_locks=check_locks)


def test_blocking_flags_sleep_in_handler():
    src = """
    import time
    class Server:
        def _handle(self, msg):
            self._slow_path()
        def _slow_path(self):
            time.sleep(1.0)
    """
    vs = _blocking_violations(src)
    assert len(vs) == 1
    assert vs[0].rule == "blocking-reachable"
    assert "time.sleep" in vs[0].message
    assert "_slow_path" in vs[0].message  # call chain is reported


def test_blocking_flags_untimed_result_and_acquire():
    src = """
    class Server:
        def _handle(self, msg):
            fut.result()
            self._lock.acquire()
    """
    reasons = {v.message.split(" reachable")[0]
               for v in _blocking_violations(src)}
    assert ".result() with no timeout" in reasons
    assert ".acquire() with no timeout" in reasons


def test_blocking_ok_with_timeouts_or_off_path():
    src = """
    import time
    class Server:
        def _handle(self, msg):
            fut.result(timeout=5.0)
            self._lock.acquire(timeout=1.0)
        def unrelated(self):
            time.sleep(9.9)
    """
    assert _blocking_violations(src) == []


def test_blocking_wildcard_entry_matches_op_handlers():
    src = """
    import time
    class Server:
        def _op_slow(self, msg):
            time.sleep(0.5)
        def _op_fast(self, msg):
            return 1
    """
    vs = _blocking_violations(src, entries=("Server._op_*",))
    assert len(vs) == 1 and "_op_slow" in vs[0].message


def test_blocking_flags_fsync():
    src = """
    import os
    class Server:
        def _handle(self, msg):
            os.fsync(fd)
    """
    vs = _blocking_violations(src)
    assert len(vs) == 1 and "os.fsync" in vs[0].message


def _cross_fixture(tmp_path, helper_body):
    for pkg in ("ray_tpu", "ray_tpu/core", "ray_tpu/util"):
        (tmp_path / pkg).mkdir(exist_ok=True)
        (tmp_path / pkg / "__init__.py").write_text("")
    (tmp_path / "ray_tpu" / "core" / "srv.py").write_text(
        textwrap.dedent("""
            from ray_tpu.util import helper
            from ray_tpu.util.helper import do_work
            class Server:
                def _handle(self, msg):
                    helper.do_work()
                def _handle2(self, msg):
                    do_work()
        """))
    (tmp_path / "ray_tpu" / "util" / "helper.py").write_text(
        textwrap.dedent(helper_body))
    return blocking_pass.run(
        str(tmp_path),
        entry_points={"ray_tpu/core/srv.py": ("Server._handle",
                                              "Server._handle2")},
        lock_modules=())


def test_blocking_cross_module_one_hop(tmp_path):
    vs = _cross_fixture(tmp_path, """
        import time
        def do_work():
            _inner()
        def _inner():
            time.sleep(1.0)
    """)
    # Found through BOTH import forms (module alias + imported func),
    # anchored to the target module, deduped per entry.
    assert vs and all(v.path == "ray_tpu/util/helper.py" for v in vs)
    assert any("time.sleep" in v.message and "=> helper:" in v.message
               for v in vs)


def test_blocking_cross_module_stops_after_one_hop(tmp_path):
    (tmp_path / "ray_tpu" / "util").mkdir(parents=True)
    (tmp_path / "ray_tpu" / "util" / "deep.py").write_text(
        "import time\ndef hidden():\n    time.sleep(5)\n")
    vs = _cross_fixture(tmp_path, """
        from ray_tpu.util import deep
        def do_work():
            deep.hidden()
    """)
    # helper itself has no blocking site; deep.hidden is two hops out
    # and must NOT be followed.
    assert vs == []


def test_journal_fsync_unreachable_from_receive_entries():
    # The ops journal DOES fsync (on its writer thread)...
    src = open(os.path.join(REPO_ROOT, "ray_tpu", "util",
                            "journal.py")).read()
    assert "os.fsync" in src
    # ...and journal.py's enqueue side is a declared entry-point set,
    # so the pass proves the receive path can never reach it.
    assert "ray_tpu/util/journal.py" in blocking_pass.DEFAULT_ENTRY_POINTS
    vs = blocking_pass.run(REPO_ROOT)
    fsync_hits = [v.render() for v in vs if "os.fsync" in v.message]
    assert fsync_hits == []


def test_blocking_under_lock():
    src = """
    import time
    class Store:
        def put(self, k, v):
            with self._lock:
                time.sleep(0.1)
        def get(self, k):
            with self._lock:
                return self._d[k]
    """
    vs = _blocking_violations(src, entries=(), check_locks=True)
    assert len(vs) == 1 and vs[0].rule == "blocking-under-lock"


# --------------------------------------------------------------------------
# wire / metrics conformance
# --------------------------------------------------------------------------

def test_wire_handled_op_extraction():
    import ast
    src = textwrap.dedent("""
        class ControlServer:
            def _op_ping(self, msg):
                return {}
        def dispatch(msg):
            op = msg.get("op")
            if op == "alpha":
                return 1
            if msg.get("op") in ("beta", "gamma"):
                return 2
            if msg["op"] != "delta":
                return 3
    """)
    ops = conformance_pass.extract_handled_ops(ast.parse(src))
    assert set(ops) == {"ping", "alpha", "beta", "gamma", "delta"}


def test_wire_both_directions(tmp_path):
    (tmp_path / "handlers.py").write_text(textwrap.dedent("""
        def dispatch(op, msg):
            if op == "declared_op":
                return 1
            if op == "rogue_op":
                return 2
    """))
    vs = conformance_pass.run_wire(
        str(tmp_path), handler_modules=("handlers.py",),
        schema_ops={"declared_op", "ghost_op"})
    by_rule = {}
    for v in vs:
        by_rule.setdefault(v.rule, []).append(v)
    assert len(by_rule["wire-undeclared"]) == 1
    assert "rogue_op" in by_rule["wire-undeclared"][0].message
    assert len(by_rule["wire-unhandled"]) == 1
    assert "ghost_op" in by_rule["wire-unhandled"][0].message


def test_wire_repo_schema_covers_all_handled_ops():
    vs = conformance_pass.run_wire(REPO_ROOT)
    assert [v.render() for v in vs] == []


def test_metrics_pass_matches_legacy_checker_shape(tmp_path):
    # The shim's check() must return [] at head (it is loaded by path
    # in test_profiling_watchdog.py).
    assert conformance_pass.metrics_problems(REPO_ROOT) == []


def test_wire_corpus_is_fresh():
    with open(os.path.join(REPO_ROOT, "WIRE_CONFORMANCE.json")) as f:
        committed = json.load(f)
    assert committed == conformance_pass.build_corpus()


# --------------------------------------------------------------------------
# log_once (the swallow-fix utility)
# --------------------------------------------------------------------------

def test_log_once_rate_limits_per_cause():
    import logging

    from ray_tpu.core import log_once

    log_once.reset()
    records = []

    class _H(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("test_log_once")
    logger.addHandler(_H())
    logger.setLevel(logging.WARNING)
    try:
        exc = ValueError("boom")
        assert log_once.warn_once(logger, "t", exc, "first")
        assert not log_once.warn_once(logger, "t", exc, "second")
        # distinct cause -> logs
        assert log_once.warn_once(logger, "t", KeyError("k"), "third")
        # zero interval -> window expired, suppressed count surfaces
        assert log_once.warn_once(logger, "t", exc, "fourth",
                                  interval_s=0.0)
        assert len(records) == 3
        assert "boom" in records[0]
        assert "[1 similar suppressed]" in records[2]
    finally:
        log_once.reset()


# --------------------------------------------------------------------------
# the real repo, through the real runner
# --------------------------------------------------------------------------

def test_runner_whole_repo_exits_zero(capsys):
    rc = raylint_main(["--root", REPO_ROOT, "-q"])
    out = capsys.readouterr().out
    assert rc == 0, f"raylint regressions:\n{out}"


def test_runner_exits_nonzero_on_seeded_violations(tmp_path):
    # One seeded violation per pass family, reported with file:line.
    root = _knob_fixture(tmp_path)
    bad = tmp_path / "ray_tpu" / "core" / "bad.py"
    bad.write_text(textwrap.dedent("""
        import os, time
        UNREG = os.environ.get("RAY_TPU_NOT_A_KNOB", "")
        class ControlServer:
            def _op_rogue(self, msg):
                time.sleep(1)
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    import ray_tpu.analysis.blocking_pass as bp
    import ray_tpu.analysis.conformance_pass as cp
    entry = {"ray_tpu/core/bad.py": ("ControlServer._op_*",)}
    violations = []
    violations += knob_pass.run(root)
    violations += except_pass.run(root)
    violations += bp.run(root, entry_points=entry, lock_modules=())
    violations += cp.run_wire(root,
                              handler_modules=("ray_tpu/core/bad.py",),
                              schema_ops=set())
    rules = {v.rule for v in violations}
    assert {"knob-unregistered", "swallow", "blocking-reachable",
            "wire-undeclared"} <= rules
    for v in violations:
        assert v.path and v.line >= 1 and ":" in v.render()


def test_ratchet_stale_entry_fails_until_shrunk(tmp_path, capsys):
    root = tmp_path / "r"
    (root / "ray_tpu").mkdir(parents=True)
    (root / "ray_tpu" / "__init__.py").write_text("")
    (root / "ray_tpu" / "m.py").write_text("def f():\n    return 1\n")
    bl = tmp_path / "baseline.json"
    acore.save_baseline(
        {"swallow::ray_tpu/m.py::except Exception:": 1}, str(bl))
    # No live violation matches the frozen entry -> the run fails.
    rc = raylint_main(["--root", str(root), "--passes", "except",
                       "--baseline", str(bl), "-q"])
    assert rc == 1
    assert "stale baseline entry" in capsys.readouterr().out
    # A pass that does not own the rule does not see the debt.
    assert raylint_main(["--root", str(root), "--passes", "knobs",
                         "--baseline", str(bl), "-q"]) in (0, 1)
    # --update-baseline shrinks freely; the run is then clean.
    assert raylint_main(["--root", str(root), "--passes", "except",
                         "--baseline", str(bl), "-q",
                         "--update-baseline"]) == 0
    assert acore.load_baseline(str(bl)) == {}
    assert raylint_main(["--root", str(root), "--passes", "except",
                         "--baseline", str(bl), "-q"]) == 0


def test_ratchet_update_refuses_growth(tmp_path, capsys):
    root = tmp_path / "r"
    (root / "ray_tpu").mkdir(parents=True)
    (root / "ray_tpu" / "__init__.py").write_text("")
    (root / "ray_tpu" / "m.py").write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    bl = tmp_path / "baseline.json"
    acore.save_baseline({}, str(bl))
    # Growing the baseline (0 -> 1 entries) is refused...
    rc = raylint_main(["--root", str(root), "--passes", "except",
                       "--baseline", str(bl), "-q", "--update-baseline"])
    assert rc == 1
    assert "refusing to grow" in capsys.readouterr().err
    assert acore.load_baseline(str(bl)) == {}
    # ...unless growth is explicitly allowed (new-rule bootstrap).
    rc = raylint_main(["--root", str(root), "--passes", "except",
                       "--baseline", str(bl), "-q", "--update-baseline",
                       "--allow-baseline-growth"])
    assert rc == 0
    assert sum(acore.load_baseline(str(bl)).values()) == 1


def test_runner_cli_list_passes():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "raylint.py"),
         "--list-passes"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert set(out.stdout.split()) == set(PASSES)


def test_baseline_file_is_loadable_and_nonempty():
    entries = acore.load_baseline()
    assert entries, "analysis/baseline.json missing or empty"
    assert all(isinstance(n, int) and n >= 1 for n in entries.values())
    families = {k.split("::", 1)[0].split("-")[0] for k in entries}
    assert "swallow" in families
