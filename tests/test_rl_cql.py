"""CQL (offline RL) tests — SURVEY.md §2.3 L5 algorithm family."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl.episode import SingleAgentEpisode


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _bandit_episodes(n_episodes=24, T=16, seed=0):
    """1-step-ish continuous bandit on Pendulum's spaces: reward
    -(a - 0.5)^2, dataset actions confined to [0.2, 0.8]."""
    rng = np.random.default_rng(seed)
    episodes = []
    for _ in range(n_episodes):
        ep = SingleAgentEpisode()
        obs = rng.normal(size=(T + 1, 3)).astype(np.float32)
        ep.add_reset(obs[0])
        for t in range(T):
            a = float(rng.uniform(0.2, 0.8))
            ep.add_step(obs[t + 1], np.array([a], dtype=np.float32),
                        -(a - 0.5) ** 2, terminated=t == T - 1)
        episodes.append(ep)
    return episodes


def test_cql_requires_offline_data():
    from ray_tpu.rl.algorithms import CQLConfig

    with pytest.raises(ValueError, match="offline"):
        CQLConfig().environment("Pendulum-v1").build()


def test_cql_trains_and_suppresses_ood_q():
    from ray_tpu.rl.algorithms import CQLConfig

    config = (CQLConfig()
              .environment("Pendulum-v1")
              .offline_data(input_episodes=_bandit_episodes())
              .training(train_batch_size=64, lr=3e-4, gamma=0.0,
                        hidden_sizes=(32, 32), num_sgd_iter=40,
                        cql_alpha=2.0)
              .debugging(seed=0))
    algo = config.build()
    result = {}
    for _ in range(8):
        result = algo.step()
    assert "cql_penalty" in result and np.isfinite(result["cql_penalty"])

    # Q(dataset-support action) must beat Q(out-of-distribution action).
    import jax.numpy as jnp

    params = algo.learner_group.get_weights()
    spec = algo._spec
    obs = jnp.asarray(np.random.default_rng(1).normal(
        size=(64, 3)).astype(np.float32))
    q_in = np.asarray(spec.q_value(
        params["q1"], obs, jnp.full((64, 1), 0.5)))
    q_ood = np.asarray(spec.q_value(
        params["q1"], obs, jnp.full((64, 1), -1.9)))
    algo.stop()
    assert q_in.mean() > q_ood.mean() + 0.1, (q_in.mean(), q_ood.mean())


def test_cql_never_samples_env():
    from ray_tpu.rl.algorithms import CQLConfig

    config = (CQLConfig()
              .environment("Pendulum-v1")
              .offline_data(input_episodes=_bandit_episodes(4, 8))
              .training(train_batch_size=32, num_sgd_iter=2,
                        hidden_sizes=(16,))
              .debugging(seed=0))
    algo = config.build()
    before = algo.env_runner_group.local_runner.metrics[
        "num_env_steps_sampled_lifetime"]
    algo.step()
    after = algo.env_runner_group.local_runner.metrics[
        "num_env_steps_sampled_lifetime"]
    algo.stop()
    assert before == after == 0


def test_cql_trains_from_written_dataset_file(tmp_path):
    """Offline pipeline end to end (VERDICT r3 item 5): episodes are
    written as a ray_tpu.data parquet transition dataset, CQL reads the
    directory back through the data layer and trains from it (reference
    rllib/offline/offline_data.py over ray.data)."""
    import numpy as np

    from ray_tpu.rl.algorithms import CQLConfig
    from ray_tpu.rl.episode import SingleAgentEpisode
    from ray_tpu.rl.offline import write_offline_dataset

    rng = np.random.default_rng(0)
    episodes = []
    for i in range(12):
        ep = SingleAgentEpisode(id=f"ep-{i}")
        obs = rng.normal(size=3).astype(np.float32)
        ep.add_reset(obs)
        for t in range(10):
            a = rng.uniform(-1, 1, size=1).astype(np.float32)
            obs = (obs + 0.1 * a.sum()).astype(np.float32)
            ep.add_step(obs, a, float(-np.abs(obs).sum()),
                        terminated=(t == 9))
        episodes.append(ep)
    path = str(tmp_path / "corpus")
    files = write_offline_dataset(episodes, path, format="parquet")
    assert files and all(f.endswith(".parquet") for f in files)

    import gymnasium as gym

    class FakeEnv(gym.Env):
        observation_space = gym.spaces.Box(-10, 10, (3,), np.float32)
        action_space = gym.spaces.Box(-1, 1, (1,), np.float32)

        def reset(self, *, seed=None, options=None):
            return np.zeros(3, np.float32), {}

        def step(self, action):
            return np.zeros(3, np.float32), 0.0, True, False, {}

    config = (CQLConfig()
              .environment(env_fn=FakeEnv)
              .training(train_batch_size=64)
              .debugging(seed=0))
    config.num_sgd_iter = 4
    config.offline_data(input_path=path)
    algo = config.build()
    m1 = algo.step()
    m2 = algo.step()
    algo.stop()
    assert np.isfinite(m1["critic_loss"]) and np.isfinite(m2["critic_loss"])
