"""Tune library tests (counterpart of python/ray/tune/tests strategy:
controller/scheduler/search correctness on an in-process cluster)."""

import json
import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig
from ray_tpu.tune.search import BasicVariantGenerator


@pytest.fixture(scope="module")
def rt():
    runtime = ray_tpu.init(num_cpus=8)
    yield runtime
    ray_tpu.shutdown()


@pytest.fixture()
def run_dir(tmp_path):
    return str(tmp_path)


# -- search spaces ----------------------------------------------------------


def test_basic_variant_grid_and_samples():
    gen = BasicVariantGenerator(seed=0)
    gen.set_space({
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.uniform(0.0, 1.0),
        "c": tune.choice(["x", "y"]),
        "nested": {"d": tune.randint(0, 10)},
    }, None, "max")
    assert gen.grid_size() == 3
    cfgs = gen.next_configs(6)
    assert sorted(c["a"] for c in cfgs) == [1, 1, 2, 2, 3, 3]
    assert all(0.0 <= c["b"] <= 1.0 for c in cfgs)
    assert all(c["c"] in ("x", "y") for c in cfgs)
    assert all(0 <= c["nested"]["d"] < 10 for c in cfgs)


def test_domains_sample_ranges():
    rng = np.random.default_rng(0)
    assert 1 <= tune.loguniform(1, 100).sample(rng) <= 100
    assert tune.quniform(0, 1, 0.25).sample(rng) in (
        0.0, 0.25, 0.5, 0.75, 1.0)
    assert 2 <= tune.lograndint(2, 64).sample(rng) <= 64


def test_sample_from_sees_config():
    gen = BasicVariantGenerator(seed=0)
    gen.set_space({
        "a": tune.grid_search([2, 4]),
        "b": tune.sample_from(lambda cfg: cfg["a"] * 10),
    }, None, "max")
    cfgs = gen.next_configs(2)
    assert all(c["b"] == c["a"] * 10 for c in cfgs)


# -- Tuner end-to-end -------------------------------------------------------


def test_tuner_function_trainable(rt, run_dir):
    def objective(config):
        for step in range(3):
            tune.report({"score": -abs(config["x"] - 2.0), "step": step})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0.0, 2.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=run_dir, name="fn"),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["score"] == 0.0
    assert all(len(r.metrics_history) == 3 for r in grid)


def test_tuner_class_trainable_with_stop(rt, run_dir):
    class Counter(tune.Trainable):
        def setup(self, config):
            self.count = 0
            self.inc = config["inc"]

        def step(self):
            self.count += self.inc
            return {"count": self.count}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump({"count": self.count}, f)

        def load_checkpoint(self, d):
            with open(os.path.join(d, "s.json")) as f:
                self.count = json.load(f)["count"]

    grid = tune.Tuner(
        Counter,
        param_space={"inc": tune.grid_search([1, 3])},
        tune_config=tune.TuneConfig(metric="count", mode="max"),
        run_config=RunConfig(storage_path=run_dir, name="cls",
                             stop={"training_iteration": 4}),
    ).fit()
    counts = sorted(r.metrics["count"] for r in grid)
    assert counts == [4, 12]
    assert all(r.checkpoint is not None for r in grid)


def test_function_checkpoint_persisted(rt, run_dir):
    def ckpt_fn(config):
        for i in range(3):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump({"i": i}, f)
            tune.report({"i": i}, checkpoint=Checkpoint.from_directory(d))

    grid = tune.Tuner(
        ckpt_fn, param_space={},
        tune_config=tune.TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(storage_path=run_dir, name="ck"),
    ).fit()
    r = grid.get_best_result()
    assert r.checkpoint is not None
    with open(os.path.join(r.checkpoint.as_directory(), "s.json")) as f:
        assert json.load(f)["i"] == 2


def test_trial_failure_retry_then_error(rt, run_dir):
    def flaky(config):
        raise RuntimeError("boom")

    grid = tune.Tuner(
        flaky, param_space={},
        tune_config=tune.TuneConfig(metric="x", mode="max"),
        run_config=RunConfig(storage_path=run_dir, name="flaky"),
    ).fit()
    assert len(grid.errors) == 1
    assert "boom" in str(grid.errors[0])


def test_experiment_state_file(rt, run_dir):
    def objective(config):
        tune.report({"v": 1})

    tune.Tuner(
        objective, param_space={},
        tune_config=tune.TuneConfig(metric="v", mode="max"),
        run_config=RunConfig(storage_path=run_dir, name="state"),
    ).fit()
    path = os.path.join(run_dir, "state", "experiment_state.json")
    with open(path) as f:
        state = json.load(f)
    assert state["trials"][0]["state"] == "TERMINATED"


# -- schedulers -------------------------------------------------------------


def test_asha_stops_weak_trials(rt, run_dir):
    def objective(config):
        for step in range(1, 21):
            tune.report({"score": config["q"] * step})

    grid = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([0.1, 0.5, 1.0, 2.0, 4.0, 8.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.AsyncHyperBandScheduler(
                grace_period=2, reduction_factor=3, max_t=20),
            max_concurrent_trials=6),
        run_config=RunConfig(storage_path=run_dir, name="asha"),
    ).fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    assert iters[0] < 20  # at least one early stop
    assert iters[-1] == 20  # best trial ran to completion


def test_pbt_exploits_upward(rt, run_dir):
    def pbt_fn(config):
        ck = tune.get_checkpoint()
        w = 0.0
        if ck:
            with open(os.path.join(ck.as_directory(), "w.json")) as f:
                w = json.load(f)["w"]
        for step in range(1, 25):
            w += config["lr"]
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "w.json"), "w") as f:
                json.dump({"w": w}, f)
            tune.report({"w": w}, checkpoint=Checkpoint.from_directory(d))

    grid = tune.Tuner(
        pbt_fn,
        param_space={"lr": tune.grid_search([0.001, 0.01, 1.0, 2.0])},
        tune_config=tune.TuneConfig(
            metric="w", mode="max",
            scheduler=tune.PopulationBasedTraining(
                perturbation_interval=5,
                hyperparam_mutations={"lr": tune.uniform(0.5, 3.0)},
                seed=0)),
        run_config=RunConfig(storage_path=run_dir, name="pbt"),
    ).fit()
    ws = sorted(r.metrics["w"] for r in grid if r.metrics and "w" in r.metrics)
    # without exploitation the lr=0.001 trial ends at w=0.024; with PBT it
    # must have been restarted from a strong donor at least once
    assert ws[0] > 1.0


def test_median_stopping(rt, run_dir):
    def objective(config):
        for step in range(1, 11):
            tune.report({"score": config["q"]})

    grid = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([0.0, 0.0, 10.0, 10.0, 10.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.MedianStoppingRule(
                grace_period=3, min_samples_required=2),
            max_concurrent_trials=5),
        run_config=RunConfig(storage_path=run_dir, name="median"),
    ).fit()
    by_q = {}
    for r in grid:
        by_q.setdefault(r.metrics["score"], []).append(
            r.metrics.get("training_iteration"))
    assert max(by_q[0.0]) < 10  # weak trials stopped early
    assert max(by_q[10.0]) == 10


def test_hyperband_rung_barrier_and_promotion(rt, run_dir):
    """Synchronous HyperBand: cohorts pause at rung boundaries; only the
    top 1/eta of each bracket's cohort continues past its first rung
    (reference tune/schedulers/hyperband.py)."""
    def objective(config):
        for step in range(1, 10):
            tune.report({"score": config["q"] * step})

    grid = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search(
            [0.1, 0.5, 1.0, 2.0, 4.0, 8.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.HyperBandScheduler(
                max_t=9, reduction_factor=3),
            max_concurrent_trials=6),
        run_config=RunConfig(storage_path=run_dir, name="hyperband"),
    ).fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    assert iters[0] < 9, iters      # some trials culled at a rung
    assert iters[-1] == 9, iters    # a survivor ran to max_t
    # The best trial (q=8.0) must have survived to max_t: score 8*9.
    best = max(grid, key=lambda r: r.metrics.get("score", -1))
    assert best.metrics["score"] == 72.0
    assert best.metrics["training_iteration"] == 9


def test_hyperband_unit_rung_math():
    from ray_tpu.tune.schedulers import CONTINUE as C
    from ray_tpu.tune.schedulers import PAUSE as P
    from ray_tpu.tune.schedulers import STOP as S
    from ray_tpu.tune.tune_controller import Trial

    sched = tune.HyperBandScheduler(max_t=9, reduction_factor=3)
    sched.set_objective("score", "max")
    trials = [Trial(trial_id=f"t{i}", config={}, trial_dir="/tmp/x")
              for i in range(3)]
    for t in trials:
        sched.on_trial_add(t)
    b = sched._by_trial["t0"]
    assert b.r >= 1
    # Nobody pauses before the rung, everyone pauses at it.
    assert sched.on_trial_result(
        trials[0], {"training_iteration": 0, "score": 1}) == C \
        or b.r <= 0
    decisions = {}
    for i, t in enumerate(trials):
        d = sched.on_trial_result(
            t, {"training_iteration": b.r, "score": float(i)})
        decisions[t.trial_id] = d
    assert all(d == P for d in decisions.values())
    # Cohort complete: top ceil(3/3)=1 continues, two stop.
    out = sched.poll_paused()
    assert sorted(out.values()) == [C, S, S]
    assert out["t2"] == C  # highest score survives
