"""Tune callback system + logger callbacks + experiment-tracker
integrations (SURVEY.md §2.3 L3/L6; reference tune/callback.py,
tune/logger/, air/integrations/{wandb,mlflow,comet}.py)."""

import csv
import json
import os
import types

import pytest

import ray_tpu
from ray_tpu.tune import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
    TuneConfig,
    Tuner,
)
from ray_tpu.train.config import RunConfig
from ray_tpu.util.integrations import (
    CometLoggerCallback,
    MlflowLoggerCallback,
    WandbLoggerCallback,
    setup_mlflow,
    setup_wandb,
)


@pytest.fixture(scope="module", autouse=True)
def _rt():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def _fit(tmp_path, callbacks, num_samples=2, trainable=None):
    if trainable is None:
        # Nested so cloudpickle ships it by value (workers cannot
        # import this test module).
        def trainable(config):
            from ray_tpu.tune.trainable import report

            for i in range(3):
                report({"score": config["x"] * (i + 1),
                        "training_iteration": i + 1})

    tuner = Tuner(
        trainable,
        param_space={"x": 1.0},
        tune_config=TuneConfig(metric="score", mode="max",
                               num_samples=num_samples),
        run_config=RunConfig(name="cb", storage_path=str(tmp_path),
                             callbacks=callbacks))
    return tuner.fit()


class _Recorder(Callback):
    def __init__(self):
        self.events = []

    def setup(self, *, run_dir, trials):
        self.events.append(("setup", run_dir))

    def on_trial_start(self, *, trial):
        self.events.append(("start", trial.trial_id))

    def on_trial_result(self, *, trial, result):
        self.events.append(("result", trial.trial_id,
                            result.get("score", result.get("loss"))))

    def on_checkpoint(self, *, trial, checkpoint_path):
        self.events.append(("checkpoint", trial.trial_id,
                            checkpoint_path))

    def on_trial_complete(self, *, trial):
        self.events.append(("complete", trial.trial_id))

    def on_trial_error(self, *, trial):
        self.events.append(("error", trial.trial_id))

    def on_experiment_end(self, *, trials):
        self.events.append(("end", len(trials)))


def test_callback_hook_ordering(tmp_path):
    rec = _Recorder()
    results = _fit(tmp_path, [rec], num_samples=1)
    assert len(results) == 1
    kinds = [e[0] for e in rec.events]
    assert kinds[0] == "setup"
    assert kinds[-1] == "end"
    assert kinds.index("start") < kinds.index("result") < \
        kinds.index("complete")
    scores = [e[2] for e in rec.events if e[0] == "result"]
    assert scores == [1.0, 2.0, 3.0]


def test_error_hook_and_containment(tmp_path):
    def failing(config):
        raise RuntimeError("boom")

    class Broken(Callback):
        def on_trial_start(self, *, trial):
            raise ValueError("bad callback")

    rec = _Recorder()
    results = _fit(tmp_path, [Broken(), rec], num_samples=1,
                   trainable=failing)
    # The broken callback is contained; the recorder still saw the run.
    assert ("error", "trial_00000") in rec.events
    assert len(results.errors) == 1


def test_on_checkpoint_fires_for_reported_and_final_saves(tmp_path):
    """on_checkpoint dispatches both for checkpoints attached to
    reports (function trainable) AND for the controller's
    completion-time save of class trainables (_save_runner_checkpoint
    — the path a function trainable never hits)."""
    def ckpt_trainable(config):
        import os as _os
        import tempfile

        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.tune.trainable import report

        d = tempfile.mkdtemp()
        with open(_os.path.join(d, "w.txt"), "w") as f:
            f.write("1")
        report({"score": 1.0},
               checkpoint=Checkpoint.from_directory(d))
        report({"score": 2.0})

    rec = _Recorder()
    results = _fit(tmp_path, [rec], num_samples=1,
                   trainable=ckpt_trainable)
    assert len(results) == 1
    ckpts = [e for e in rec.events if e[0] == "checkpoint"]
    assert len(ckpts) >= 1 and all(e[2] for e in ckpts)

    # Class trainable: NO report-attached checkpoint, so the only
    # on_checkpoint can come from the completion-time runner save.
    from ray_tpu.tune.trainable import Trainable as TuneTrainable

    class Stepper(TuneTrainable):
        def setup(self, config):
            self.i = 0

        def step(self):
            self.i += 1
            return {"score": float(self.i),
                    "done": self.i >= 2}

    rec2 = _Recorder()
    tuner = Tuner(
        Stepper,
        param_space={},
        tune_config=TuneConfig(metric="score", mode="max",
                               num_samples=1),
        run_config=RunConfig(name="cls", storage_path=str(tmp_path),
                             callbacks=[rec2],
                             stop={"score": 2.0}))
    results = tuner.fit()
    assert len(results) == 1
    ckpts2 = [e for e in rec2.events if e[0] == "checkpoint"]
    assert len(ckpts2) >= 1 and all(e[2] for e in ckpts2)


def test_json_and_csv_loggers_default(tmp_path):
    """JSON/CSV loggers are attached by DEFAULT (no callbacks arg)."""
    results = _fit(tmp_path, None, num_samples=2)
    assert len(results) == 2
    for i in range(2):
        tdir = os.path.join(str(tmp_path), "cb", f"trial_{i:05d}")
        with open(os.path.join(tdir, "result.json")) as f:
            rows = [json.loads(line) for line in f]
        assert [r["score"] for r in rows] == [1.0, 2.0, 3.0]
        assert rows[0]["trial_id"] == f"trial_{i:05d}"
        with open(os.path.join(tdir, "progress.csv"), newline="") as f:
            crows = list(csv.DictReader(f))
        assert [float(r["score"]) for r in crows] == [1.0, 2.0, 3.0]


def test_csv_logger_no_duplicate_header_after_restore(tmp_path):
    """A fresh CSVLoggerCallback (experiment restore) appends rows under
    the EXISTING header instead of writing a second one mid-file."""
    import dataclasses

    @dataclasses.dataclass
    class _T:
        trial_id: str
        trial_dir: str
        metrics_history: list

    t = _T("trial_x", str(tmp_path / "trial_x"), [])
    cb1 = CSVLoggerCallback()
    cb1.on_trial_result(trial=t, result={"score": 1.0})
    cb2 = CSVLoggerCallback()  # restored controller: fresh instance
    cb2.on_trial_result(trial=t, result={"score": 2.0})
    with open(os.path.join(t.trial_dir, "progress.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert [float(r["score"]) for r in rows] == [1.0, 2.0]


def test_default_loggers_respect_subclasses(tmp_path):
    from ray_tpu.tune.callbacks import default_callbacks

    class MyJson(JsonLoggerCallback):
        pass

    cbs = default_callbacks([MyJson()]).callbacks
    assert sum(isinstance(c, JsonLoggerCallback) for c in cbs) == 1


def test_tbx_logger_stub(tmp_path):
    writes = []

    class _Writer:
        def __init__(self, logdir=None):
            self.logdir = logdir

        def add_scalar(self, tag, value, global_step=None):
            writes.append((tag, value, global_step))

        def flush(self):
            pass

        def close(self):
            writes.append(("closed",))

    mod = types.ModuleType("tensorboardX")
    mod.SummaryWriter = _Writer
    results = _fit(tmp_path, [TBXLoggerCallback(_module=mod)],
                   num_samples=1)
    assert len(results) == 1
    scalars = [w for w in writes if w[0] == "score"]
    assert [(v, s) for _, v, s in scalars] == [(1.0, 1), (2.0, 2), (3.0, 3)]
    assert ("closed",) in writes


def test_tbx_logger_real(tmp_path):
    """tensorboardX ships in the image: the same adapter activates
    unchanged and writes real event files."""
    pytest.importorskip("tensorboardX")
    results = _fit(tmp_path, [TBXLoggerCallback()], num_samples=1)
    assert len(results) == 1
    tdir = os.path.join(str(tmp_path), "cb", "trial_00000")
    assert any(name.startswith("events.out.tfevents")
               for name in os.listdir(tdir)), os.listdir(tdir)


def test_wandb_logger_stub(tmp_path):
    runs = []

    class _Run:
        def __init__(self, name, config):
            self.name = name
            self.config = config
            self.logged = []
            self.finished = False

        def log(self, metrics):
            self.logged.append(metrics)

        def finish(self):
            self.finished = True

    mod = types.ModuleType("wandb")

    def init(project=None, group=None, name=None, config=None,
             reinit=None, **kw):
        run = _Run(name, config)
        runs.append((project, run))
        return run

    mod.init = init
    cb = WandbLoggerCallback(project="proj", _module=mod)
    results = _fit(tmp_path, [cb], num_samples=2)
    assert len(results) == 2
    assert all(p == "proj" for p, _ in runs)
    assert sorted(r.name for _, r in runs) == ["trial_00000",
                                               "trial_00001"]
    for _, run in runs:
        assert [m["score"] for m in run.logged] == [1.0, 2.0, 3.0]
        assert run.finished
    with pytest.raises(ImportError, match="CSVLoggerCallback"):
        WandbLoggerCallback(project="p")

    # User init kwargs that collide with computed ones (name/reinit)
    # override instead of raising TypeError inside the contained hook.
    runs.clear()
    cb = WandbLoggerCallback(project="proj", name="fixed", _module=mod)
    _fit(tmp_path / "w2", [cb], num_samples=1)
    assert [r.name for _, r in runs] == ["fixed"]


def test_mlflow_logger_stub(tmp_path):
    state = {"params": [], "metrics": [], "terminated": []}

    class _Info:
        def __init__(self, run_id):
            self.run_id = run_id

    class _MlRun:
        def __init__(self, run_id):
            self.info = _Info(run_id)

    class _Client:
        def __init__(self, tracking_uri=None):
            self._n = 0

        def get_experiment_by_name(self, name):
            return None

        def create_experiment(self, name):
            return "exp1"

        def create_run(self, experiment_id, tags=None):
            self._n += 1
            return _MlRun(f"run{self._n}")

        def log_param(self, run_id, k, v):
            state["params"].append((run_id, k, v))

        def log_metric(self, run_id, k, v, step=None):
            state["metrics"].append((run_id, k, v, step))

        def set_terminated(self, run_id, status=None):
            state["terminated"].append((run_id, status))

    mod = types.ModuleType("mlflow")
    mod.tracking = types.SimpleNamespace(MlflowClient=_Client)
    cb = MlflowLoggerCallback("exp", _module=mod)
    results = _fit(tmp_path, [cb], num_samples=1)
    assert len(results) == 1
    assert ("run1", "x", 1.0) in state["params"]
    scores = [(v, s) for rid, k, v, s in state["metrics"] if k == "score"]
    assert scores == [(1.0, 1), (2.0, 2), (3.0, 3)]
    assert state["terminated"] == [("run1", "FINISHED")]


def test_comet_logger_stub(tmp_path):
    exps = []

    class _Exp:
        def __init__(self, project_name=None, **kw):
            self.project = project_name
            self.name = None
            self.params = {}
            self.metrics = []
            self.ended = False
            exps.append(self)

        def set_name(self, name):
            self.name = name

        def log_parameters(self, params):
            self.params.update(params)

        def log_metrics(self, metrics, step=None):
            self.metrics.append((metrics, step))

        def end(self):
            self.ended = True

    mod = types.ModuleType("comet_ml")
    mod.Experiment = _Exp
    results = _fit(tmp_path,
                   [CometLoggerCallback(project_name="p", _module=mod)],
                   num_samples=1)
    assert len(results) == 1
    (exp,) = exps
    assert exp.name == "trial_00000" and exp.params == {"x": 1.0}
    assert [m["score"] for m, _ in exp.metrics] == [1.0, 2.0, 3.0]
    assert exp.ended


def test_train_fit_dispatches_callbacks(tmp_path):
    """Standalone JaxTrainer.fit runs the same callback surface
    (reference: Train shares RunConfig.callbacks with Tune)."""
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        for i in range(3):
            train.report({"loss": 1.0 / (i + 1),
                          "training_iteration": i + 1})

    rec = _Recorder()
    res = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="cbtrain",
                             callbacks=[rec]),
    ).fit()
    assert len(res.metrics_history) == 3
    kinds = [e[0] for e in rec.events]
    assert kinds[0] == "setup" and kinds[-1] == "end"
    assert kinds.count("result") == 3
    assert "complete" in kinds
    # Default JSON logger wrote the run's result.json too.
    with open(os.path.join(str(tmp_path), "cbtrain", "result.json")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["loss"] for r in rows] == [1.0, 0.5, 1.0 / 3.0]


def test_setup_helpers_stubs():
    mod = types.ModuleType("wandb")
    captured = {}

    def init(**kw):
        captured.update(kw)
        return "run"

    mod.init = init
    assert setup_wandb({"lr": 0.1}, project="p", trial_id="t1",
                       _module=mod) == "run"
    assert captured["config"] == {"lr": 0.1} and captured["name"] == "t1"

    ml = types.ModuleType("mlflow")
    calls = []
    ml.set_tracking_uri = lambda uri: calls.append(("uri", uri))
    ml.set_experiment = lambda name: calls.append(("exp", name))
    ml.start_run = lambda nested=False: calls.append(("run", nested)) or "r"
    ml.log_params = lambda params: calls.append(("params", params))
    assert setup_mlflow({"lr": 0.1}, experiment_name="e",
                        tracking_uri="file:///tmp/ml", _module=ml) == "r"
    assert ("exp", "e") in calls and ("params", {"lr": 0.1}) in calls
