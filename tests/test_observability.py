"""Observability stack tests: metrics, timeline, tracing, log monitor,
usage stats (SURVEY.md §5 aux subsystems / §2.2 P15–P21)."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import tracing
from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    snapshots_to_prometheus_text,
)


# ---------------------------------------------------------------------------
# Metrics: local registry + exposition
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_exposition():
    c = Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    g = Gauge("test_temperature", "deg")
    g.set(42.5)
    h = Histogram("test_latency", "s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = snapshots_to_prometheus_text(
        [c.snapshot(), g.snapshot(), h.snapshot()])
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert 'test_requests_total{route="/b"} 1.0' in text
    assert "test_temperature 42.5" in text
    assert 'test_latency_bucket{le="0.1"} 1' in text
    assert 'test_latency_bucket{le="1.0"} 2' in text
    assert 'test_latency_bucket{le="+Inf"} 3' in text
    assert "test_latency_count 3" in text
    assert "# TYPE test_requests_total counter" in text


def test_metric_tag_validation():
    c = Counter("test_tags_strict", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(tags={"other": "x"})
    with pytest.raises(ValueError):
        c.inc(-1.0)
    c.set_default_tags({"k": "v"})
    c.inc()
    assert c.snapshot()["series"][(("k", "v"),)] == 1.0


@pytest.mark.usefixtures("ray_start_regular")
def test_metrics_aggregate_across_workers():
    """User metrics recorded inside worker processes surface in the
    driver-side aggregation (KV publish path)."""

    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter, publish_now

        c = Counter("test_worker_events", tag_keys=())
        c.inc(5.0)
        assert publish_now()
        return True

    assert ray_tpu.get(record.remote())
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    text = metrics_mod.aggregate_prometheus_text(rt)
    assert "test_worker_events 5.0" in text
    # Built-in state gauges ride along.
    assert "ray_tpu_tasks" in text
    assert "ray_tpu_nodes" in text


# ---------------------------------------------------------------------------
# Timeline
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_timeline_chrome_trace(tmp_path):
    @ray_tpu.remote
    def work(x):
        time.sleep(0.05)
        return x

    ray_tpu.get([work.remote(i) for i in range(3)])
    from ray_tpu.util.timeline import timeline

    path = str(tmp_path / "trace.json")
    # The task_done control message can land just after get() returns;
    # poll briefly until all three records carry finish timestamps.
    deadline = time.time() + 5
    while True:
        events = timeline(path)
        done = [e for e in events
                if e.get("ph") == "X" and e["cat"] == "task"]
        if len(done) >= 3 or time.time() > deadline:
            break
        time.sleep(0.05)
    with open(path) as f:
        assert json.load(f) == events
    slices = [e for e in events if e.get("ph") == "X" and e["cat"] == "task"]
    assert len(slices) >= 3
    for e in slices:
        assert e["dur"] >= 0.05 * 1e6 * 0.5  # at least ~the sleep
        assert e["args"]["task_id"]
    assert any(e.get("ph") == "M" for e in events)  # row labels


# ---------------------------------------------------------------------------
# Tracing
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_tracing_spans_and_submit_instrumentation(tmp_path):
    tracing.clear_spans()
    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def traced_task():
            return 1

        with tracing.trace_span("outer", {"step": "1"}):
            with tracing.trace_span("inner"):
                ref = traced_task.remote()
        ray_tpu.get(ref)
        spans = tracing.get_spans()
        names = [s["name"] for s in spans]
        assert "outer" in names and "inner" in names
        assert any(n.startswith("submit:") for n in names)
        # Nesting: inner's parent is outer; submit's parent is inner.
        by_name = {s["name"]: s for s in spans}
        assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
        submit = next(s for s in spans if s["name"].startswith("submit:"))
        assert submit["parent_id"] == by_name["inner"]["span_id"]
        # Chrome export merges spans + cluster task slices.
        path = str(tmp_path / "spans.json")
        n = tracing.export_chrome_trace(path)
        assert n >= len(spans)
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()


def test_tracing_disabled_is_noop():
    tracing.clear_spans()
    with tracing.trace_span("nothing"):
        pass
    assert tracing.get_spans() == []


# ---------------------------------------------------------------------------
# Log monitor
# ---------------------------------------------------------------------------

def test_log_monitor_streams_worker_output(tmp_path, capsys):
    import io

    from ray_tpu.core.log_monitor import LogMonitor

    logs = tmp_path / "logs"
    logs.mkdir()
    out = io.StringIO()
    mon = LogMonitor(str(tmp_path), out=out, err=out).start()
    try:
        with open(logs / "worker-abcdef012345.out", "w") as f:
            f.write("hello from worker\n")
        deadline = time.time() + 5
        while "hello from worker" not in out.getvalue():
            assert time.time() < deadline, out.getvalue()
            time.sleep(0.05)
        assert "(abcdef01)" in out.getvalue()
    finally:
        mon.stop()


@pytest.mark.usefixtures("ray_start_regular")
def test_worker_prints_reach_driver():
    """End to end: a task's print() lands in the worker's session log file
    and a monitor attached to the live session streams it. (The built-in
    monitor prints to the real stdout, which pytest's capture layers hide
    from fixtures — so attach a second monitor with an explicit sink.)"""
    import io

    from ray_tpu.core.log_monitor import LogMonitor
    from ray_tpu.core.runtime import get_runtime

    out = io.StringIO()
    mon = LogMonitor(get_runtime().session_dir, out=out, err=out).start()
    try:
        @ray_tpu.remote
        def chatty():
            print("WORKER_SAYS_HI")
            return 0

        ray_tpu.get(chatty.remote())
        deadline = time.time() + 5
        while "WORKER_SAYS_HI" not in out.getvalue():
            assert time.time() < deadline, out.getvalue()
            time.sleep(0.1)
    finally:
        mon.stop()


# ---------------------------------------------------------------------------
# Usage stats
# ---------------------------------------------------------------------------

def test_usage_stats_report(tmp_path):
    from ray_tpu.util import usage_stats

    usage_stats.record_library_usage("testlib")
    usage_stats.record_extra_usage_tag("mesh_axes", "data,fsdp")
    path = usage_stats.write_usage_report(str(tmp_path))
    with open(path) as f:
        report = json.load(f)
    assert report["counters"].get("library:testlib", 0) >= 1
    assert report["tags"]["mesh_axes"] == "data,fsdp"


# ---------------------------------------------------------------------------
# Dashboard endpoints
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_dashboard_metrics_and_timeline_endpoints():
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    dash = Dashboard(rt)
    try:
        text = urllib.request.urlopen(dash.url + "/metrics").read().decode()
        assert "ray_tpu_tasks" in text
        tl = json.loads(
            urllib.request.urlopen(dash.url + "/api/timeline").read())
        assert isinstance(tl, list) and len(tl) >= 1
    finally:
        dash.stop()


# ---------------------------------------------------------------------------
# On-demand worker profiling (reference: dashboard reporter
# profile_manager.py py-spy/memray; SURVEY §5 TPU-native jax.profiler add)
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_stack_profile_of_busy_worker():
    import time as _time

    import ray_tpu
    from ray_tpu.state.api import list_workers, profile_worker

    @ray_tpu.remote
    def snooze_for_profiler():
        _time.sleep(4.0)
        return 1

    ref = snooze_for_profiler.remote()
    # Wait until a pool worker is busy with it.
    deadline = _time.time() + 15
    busy = None
    while busy is None and _time.time() < deadline:
        # "leased" = executing via the owner-direct lease path
        busy = next((w for w in list_workers()
                     if w["kind"] == "pool"
                     and w["state"] in ("busy", "leased")),
                    None)
        _time.sleep(0.05)
    assert busy is not None
    dump = profile_worker(busy["worker_id"], kind="stack")
    assert "snooze_for_profiler" in dump, dump[:2000]
    assert "Thread" in dump
    assert ray_tpu.get(ref) == 1


@pytest.mark.usefixtures("ray_start_regular")
def test_jax_trace_profile_of_driver():
    """jax_trace writes an xplane trace dir; profiling the driver keeps
    the test hermetic (jax is already imported here)."""
    import os as _os

    import ray_tpu
    from ray_tpu.state.api import profile_worker

    rt = ray_tpu.init()
    out_dir = profile_worker(rt.core.worker_hex, kind="jax_trace",
                             duration_s=0.3)
    assert _os.path.isdir(out_dir), out_dir
    # The profiler wrote something (plugins/profile/... xplane files).
    found = [f for _, _, fs in _os.walk(out_dir) for f in fs]
    assert found, f"empty trace dir {out_dir}"


@pytest.mark.usefixtures("ray_start_regular")
def test_profile_unknown_worker_errors():
    import pytest as _pytest

    from ray_tpu.state.api import profile_worker

    with _pytest.raises(Exception, match="no live worker"):
        profile_worker("ff" * 14)


def test_logging_config_structured_workers():
    """ray_tpu.LoggingConfig (counterpart of ray.LoggingConfig,
    _private/ray_logging/): JSON encoding + level apply to the driver
    and propagate to workers via the session environment."""
    import json
    import logging

    import ray_tpu
    from ray_tpu.core.logging_config import JsonFormatter, LoggingConfig

    # Formatter unit: record -> one JSON object with context fields.
    fmt = JsonFormatter(extra_attrs=("lineno",))
    rec = logging.LogRecord("my.logger", logging.WARNING, __file__, 42,
                            "boom %s", ("x",), None)
    obj = json.loads(fmt.format(rec))
    assert obj["levelname"] == "WARNING"
    assert obj["name"] == "my.logger"
    assert obj["message"] == "boom x"
    assert obj["lineno"] == 42

    with pytest.raises(ValueError):
        LoggingConfig(encoding="YAML")

    root = logging.getLogger()
    prev_level = root.level
    prev_formatters = [(h, h.formatter) for h in root.handlers]
    ray_tpu.init(num_cpus=2, log_to_driver=False,
                 logging_config=LoggingConfig(encoding="JSON",
                                              log_level="DEBUG"))
    try:
        assert logging.getLogger().level == logging.DEBUG

        @ray_tpu.remote
        def probe():
            import json as _json
            import logging as _logging
            import os as _os

            root = _logging.getLogger()
            h = root.handlers[0]
            rec = _logging.LogRecord("w", _logging.INFO, "f", 1,
                                     "from-worker", (), None)
            return {
                "level": root.level,
                "formatted": h.formatter.format(rec),
                "env": _os.environ.get("RAY_TPU_LOGGING_CONFIG", ""),
            }

        out = ray_tpu.get(probe.remote(), timeout=120)
        assert out["level"] == logging.DEBUG
        parsed = json.loads(out["formatted"])
        assert parsed["message"] == "from-worker"
        assert parsed.get("worker_id")  # executing-context join key
        assert "JSON" in out["env"]
    finally:
        ray_tpu.shutdown()
        import os

        assert "RAY_TPU_LOGGING_CONFIG" not in os.environ
        root.setLevel(prev_level)  # don't leak DEBUG into later tests
        for h, f in prev_formatters:
            h.setFormatter(f)
