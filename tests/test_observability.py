"""Observability stack tests: metrics, timeline, tracing, log monitor,
usage stats (SURVEY.md §5 aux subsystems / §2.2 P15–P21)."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import tracing
from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    snapshots_to_prometheus_text,
)


# ---------------------------------------------------------------------------
# Metrics: local registry + exposition
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_exposition():
    c = Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    g = Gauge("test_temperature", "deg")
    g.set(42.5)
    h = Histogram("test_latency", "s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = snapshots_to_prometheus_text(
        [c.snapshot(), g.snapshot(), h.snapshot()])
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert 'test_requests_total{route="/b"} 1.0' in text
    assert "test_temperature 42.5" in text
    assert 'test_latency_bucket{le="0.1"} 1' in text
    assert 'test_latency_bucket{le="1.0"} 2' in text
    assert 'test_latency_bucket{le="+Inf"} 3' in text
    assert "test_latency_count 3" in text
    assert "# TYPE test_requests_total counter" in text


def test_metric_tag_validation():
    c = Counter("test_tags_strict", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(tags={"other": "x"})
    with pytest.raises(ValueError):
        c.inc(-1.0)
    c.set_default_tags({"k": "v"})
    c.inc()
    assert c.snapshot()["series"][(("k", "v"),)] == 1.0


@pytest.mark.usefixtures("ray_start_regular")
def test_metrics_aggregate_across_workers():
    """User metrics recorded inside worker processes surface in the
    driver-side aggregation (KV publish path)."""

    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter, publish_now

        c = Counter("test_worker_events", tag_keys=())
        c.inc(5.0)
        assert publish_now()
        return True

    assert ray_tpu.get(record.remote())
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    text = metrics_mod.aggregate_prometheus_text(rt)
    assert "test_worker_events 5.0" in text
    # Built-in state gauges ride along.
    assert "ray_tpu_tasks" in text
    assert "ray_tpu_nodes" in text


# ---------------------------------------------------------------------------
# Timeline
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_timeline_chrome_trace(tmp_path):
    @ray_tpu.remote
    def work(x):
        time.sleep(0.05)
        return x

    ray_tpu.get([work.remote(i) for i in range(3)])
    from ray_tpu.util.timeline import timeline

    path = str(tmp_path / "trace.json")
    # The task_done control message can land just after get() returns;
    # poll briefly until all three records carry finish timestamps.
    deadline = time.time() + 5
    while True:
        events = timeline(path)
        done = [e for e in events
                if e.get("ph") == "X" and e["cat"] == "task"]
        if len(done) >= 3 or time.time() > deadline:
            break
        time.sleep(0.05)
    with open(path) as f:
        assert json.load(f) == events
    slices = [e for e in events if e.get("ph") == "X" and e["cat"] == "task"]
    assert len(slices) >= 3
    for e in slices:
        assert e["dur"] >= 0.05 * 1e6 * 0.5  # at least ~the sleep
        assert e["args"]["task_id"]
    assert any(e.get("ph") == "M" for e in events)  # row labels


# ---------------------------------------------------------------------------
# Tracing
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_tracing_spans_and_submit_instrumentation(tmp_path):
    tracing.clear_spans()
    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def traced_task():
            return 1

        with tracing.trace_span("outer", {"step": "1"}):
            with tracing.trace_span("inner"):
                ref = traced_task.remote()
        ray_tpu.get(ref)
        spans = tracing.get_spans()
        names = [s["name"] for s in spans]
        assert "outer" in names and "inner" in names
        assert any(n.startswith("submit:") for n in names)
        # Nesting: inner's parent is outer; submit's parent is inner.
        by_name = {s["name"]: s for s in spans}
        assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
        submit = next(s for s in spans if s["name"].startswith("submit:"))
        assert submit["parent_id"] == by_name["inner"]["span_id"]
        # Chrome export merges spans + cluster task slices.
        path = str(tmp_path / "spans.json")
        n = tracing.export_chrome_trace(path)
        assert n >= len(spans)
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()


def test_tracing_disabled_is_noop():
    tracing.clear_spans()
    with tracing.trace_span("nothing"):
        pass
    assert tracing.get_spans() == []


# ---------------------------------------------------------------------------
# Log monitor
# ---------------------------------------------------------------------------

def test_log_monitor_streams_worker_output(tmp_path, capsys):
    import io

    from ray_tpu.core.log_monitor import LogMonitor

    logs = tmp_path / "logs"
    logs.mkdir()
    out = io.StringIO()
    mon = LogMonitor(str(tmp_path), out=out, err=out).start()
    try:
        with open(logs / "worker-abcdef012345.out", "w") as f:
            f.write("hello from worker\n")
        deadline = time.time() + 5
        while "hello from worker" not in out.getvalue():
            assert time.time() < deadline, out.getvalue()
            time.sleep(0.05)
        assert "(abcdef01)" in out.getvalue()
    finally:
        mon.stop()


@pytest.mark.usefixtures("ray_start_regular")
def test_worker_prints_reach_driver():
    """End to end: a task's print() lands in the worker's session log file
    and a monitor attached to the live session streams it. (The built-in
    monitor prints to the real stdout, which pytest's capture layers hide
    from fixtures — so attach a second monitor with an explicit sink.)"""
    import io

    from ray_tpu.core.log_monitor import LogMonitor
    from ray_tpu.core.runtime import get_runtime

    out = io.StringIO()
    mon = LogMonitor(get_runtime().session_dir, out=out, err=out).start()
    try:
        @ray_tpu.remote
        def chatty():
            print("WORKER_SAYS_HI")
            return 0

        ray_tpu.get(chatty.remote())
        deadline = time.time() + 5
        while "WORKER_SAYS_HI" not in out.getvalue():
            assert time.time() < deadline, out.getvalue()
            time.sleep(0.1)
    finally:
        mon.stop()


# ---------------------------------------------------------------------------
# Usage stats
# ---------------------------------------------------------------------------

def test_usage_stats_report(tmp_path):
    from ray_tpu.util import usage_stats

    usage_stats.record_library_usage("testlib")
    usage_stats.record_extra_usage_tag("mesh_axes", "data,fsdp")
    path = usage_stats.write_usage_report(str(tmp_path))
    with open(path) as f:
        report = json.load(f)
    assert report["counters"].get("library:testlib", 0) >= 1
    assert report["tags"]["mesh_axes"] == "data,fsdp"


# ---------------------------------------------------------------------------
# Dashboard endpoints
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_dashboard_metrics_and_timeline_endpoints():
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    dash = Dashboard(rt)
    try:
        text = urllib.request.urlopen(dash.url + "/metrics").read().decode()
        assert "ray_tpu_tasks" in text
        tl = json.loads(
            urllib.request.urlopen(dash.url + "/api/timeline").read())
        assert isinstance(tl, list) and len(tl) >= 1
    finally:
        dash.stop()


# ---------------------------------------------------------------------------
# On-demand worker profiling (reference: dashboard reporter
# profile_manager.py py-spy/memray; SURVEY §5 TPU-native jax.profiler add)
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_stack_profile_of_busy_worker():
    import time as _time

    import ray_tpu
    from ray_tpu.state.api import list_workers, profile_worker

    @ray_tpu.remote
    def snooze_for_profiler():
        _time.sleep(4.0)
        return 1

    ref = snooze_for_profiler.remote()
    # Wait until a pool worker is busy with it.
    deadline = _time.time() + 15
    busy = None
    while busy is None and _time.time() < deadline:
        # "leased" = executing via the owner-direct lease path
        busy = next((w for w in list_workers()
                     if w["kind"] == "pool"
                     and w["state"] in ("busy", "leased")),
                    None)
        _time.sleep(0.05)
    assert busy is not None
    dump = profile_worker(busy["worker_id"], kind="stack")
    assert "snooze_for_profiler" in dump, dump[:2000]
    assert "Thread" in dump
    assert ray_tpu.get(ref) == 1


@pytest.mark.usefixtures("ray_start_regular")
def test_jax_trace_profile_of_driver():
    """jax_trace writes an xplane trace dir; profiling the driver keeps
    the test hermetic (jax is already imported here)."""
    import os as _os

    import ray_tpu
    from ray_tpu.state.api import profile_worker

    rt = ray_tpu.init()
    out_dir = profile_worker(rt.core.worker_hex, kind="jax_trace",
                             duration_s=0.3)
    assert _os.path.isdir(out_dir), out_dir
    # The profiler wrote something (plugins/profile/... xplane files).
    found = [f for _, _, fs in _os.walk(out_dir) for f in fs]
    assert found, f"empty trace dir {out_dir}"


@pytest.mark.usefixtures("ray_start_regular")
def test_profile_unknown_worker_errors():
    import pytest as _pytest

    from ray_tpu.state.api import profile_worker

    with _pytest.raises(Exception, match="no live worker"):
        profile_worker("ff" * 14)


# ---------------------------------------------------------------------------
# Cross-process trace propagation (tracing.py trace_ctx riding TaskSpecs)
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_trace_propagation_driver_worker_nested():
    """A driver→worker→nested-task chain yields task records sharing ONE
    trace_id with parent links pointing back through the chain to the
    driver's submit span — no extra wire round-trips involved."""
    tracing.clear_spans()
    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def leaf_task():
            return 1

        @ray_tpu.remote
        def branch_task():
            return ray_tpu.get(leaf_task.remote())

        with tracing.trace_span("root"):
            ref = branch_task.remote()
        assert ray_tpu.get(ref) == 1

        from ray_tpu.state.api import list_tasks
        by = {}
        deadline = time.time() + 10
        while time.time() < deadline:
            by = {}
            for r in list_tasks():
                nm = r.get("name") or ""
                if r.get("trace_id") and r.get("span_id"):
                    if "branch_task" in nm:
                        by["branch"] = r
                    elif "leaf_task" in nm:
                        by["leaf"] = r
            if len(by) == 2 and by["leaf"].get("parent_span_id"):
                break
            time.sleep(0.05)
        assert len(by) == 2, f"records missing trace fields: {by}"
        branch, leaf = by["branch"], by["leaf"]
        # One trace across all three processes.
        assert branch["trace_id"] == leaf["trace_id"]
        # Nested task's parent is the branch task's execution span.
        assert leaf["parent_span_id"] == branch["span_id"]
        # Branch task's parent is the driver's submit span.
        submit = next(s for s in tracing.get_spans()
                      if s["name"].startswith("submit:")
                      and "branch_task" in s["name"])
        assert branch["parent_span_id"] == submit["span_id"]
        assert submit["trace_id"] == branch["trace_id"]
        # The submit span nests under the user's root span.
        root = next(s for s in tracing.get_spans() if s["name"] == "root")
        assert submit["parent_id"] == root["span_id"]
        # get_task surfaces the same record by id.
        from ray_tpu.state.api import get_task
        rec = get_task(branch["task_id"])
        assert rec is not None and rec["trace_id"] == branch["trace_id"]
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()


def test_span_ring_bounded(monkeypatch):
    """Long-running drivers must not leak spans: the ring caps at
    RAY_TPU_TRACE_MAX_SPANS and counts evictions."""
    monkeypatch.setenv("RAY_TPU_TRACE_MAX_SPANS", "16")
    tracing.clear_spans()
    tracing.enable_tracing()  # re-reads the cap
    try:
        for i in range(40):
            tracing.record_span(f"s{i}", 0.0, 0.0)
        spans = tracing.get_spans()
        assert len(spans) == 16
        assert tracing.dropped_span_count() == 24
        # Oldest evicted, newest kept.
        assert spans[-1]["name"] == "s39"
        assert spans[0]["name"] == "s24"
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()
        monkeypatch.delenv("RAY_TPU_TRACE_MAX_SPANS")
        tracing.enable_tracing()
        tracing.disable_tracing()


# ---------------------------------------------------------------------------
# Wire-level metrics (rpc.py WIRE → metrics exposition)
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_wire_metrics_exported_after_burst():
    """After a task burst, /metrics-style aggregation exposes nonzero
    rpc frame/batch counters straight from the rpc layer."""

    @ray_tpu.remote
    def noop(i):
        return i

    assert ray_tpu.get([noop.remote(i) for i in range(100)]) == \
        list(range(100))
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    text = metrics_mod.aggregate_prometheus_text(rt)
    assert 'rpc_frames_total{direction="sent"}' in text
    assert "rpc_batch_size_count" in text
    sent = float(next(
        line.split()[-1] for line in text.splitlines()
        if line.startswith('rpc_frames_total{direction="sent"}')))
    assert sent > 0
    recv = float(next(
        line.split()[-1] for line in text.splitlines()
        if line.startswith('rpc_frames_total{direction="received"}')))
    assert recv > 0
    assert "rpc_frames_by_kind_total" in text


def test_wire_snapshot_shapes():
    from ray_tpu.core import rpc

    snaps = rpc.wire_metric_snapshots()
    names = {s["name"] for s in snaps}
    assert {"rpc_frames_total", "rpc_msgs_total", "rpc_batches_total",
            "rpc_bytes_total", "rpc_batch_size"} <= names
    hist = next(s for s in snaps if s["name"] == "rpc_batch_size")
    assert hist["kind"] == "histogram"
    assert len(hist["boundaries"]) + 1 == len(hist["series"][()][0])
    # Renders cleanly through the standard exposition path.
    text = snapshots_to_prometheus_text(snaps)
    assert "# TYPE rpc_batch_size histogram" in text


# ---------------------------------------------------------------------------
# Batched task-event streaming (worker → head delta vectors)
# ---------------------------------------------------------------------------

def test_head_frames_merges_task_event_runs():
    """Unit: a run of queued task_event deltas collapses into ONE
    task_events frame, with same-task deltas merged (later keys overlay,
    earlier keys like the arrival timestamp survive)."""
    from ray_tpu.core.runtime import CoreClient

    items = [
        ("task_event", {"task_id": "aa", "state": "RECEIVED",
                        "received": 1.0}),
        ("task_event", {"task_id": "bb", "state": "RECEIVED",
                        "received": 2.0}),
        ("task_event", {"task_id": "aa", "state": "RUNNING",
                        "start": 1.5}),
        ("task_event", {"task_id": "aa", "state": "FINISHED",
                        "start": 1.5, "end": 1.9}),
    ]
    frames = [msg for _, msg in CoreClient._head_frames(items)]
    assert len(frames) == 1
    assert frames[0]["op"] == "task_events"
    events = {e["task_id"]: e for e in frames[0]["events"]}
    assert len(events) == 2
    # Merged delta keeps the arrival time AND the final state.
    assert events["aa"]["state"] == "FINISHED"
    assert events["aa"]["received"] == 1.0
    assert events["aa"]["end"] == 1.9
    # First-seen order preserved.
    assert [e["task_id"] for e in frames[0]["events"]] == ["aa", "bb"]


@pytest.mark.usefixtures("ray_start_regular")
def test_task_event_delta_batching_under_burst():
    """A burst of N lease-path tasks reaches the head in far fewer
    task_events frames than tasks (the events ride the coalescing
    flusher as delta vectors) — the streaming analogue of
    test_rpc_batching's refcount-netting assertion."""
    from ray_tpu.core.runtime import get_runtime
    rt = get_runtime()
    ctl = getattr(rt, "control", None)
    if ctl is None or ctl._m_task_events is None:
        pytest.skip("needs an in-process head with metrics")

    def total(counter):
        return sum(counter.snapshot()["series"].values() or [0.0])

    ev0, fr0 = total(ctl._m_task_events), total(ctl._m_task_event_frames)

    @ray_tpu.remote
    def tick(i):
        return i

    n = 300
    assert ray_tpu.get([tick.remote(i) for i in range(n)]) == list(range(n))
    deadline = time.time() + 10
    while time.time() < deadline:
        events = total(ctl._m_task_events) - ev0
        frames = total(ctl._m_task_event_frames) - fr0
        # Every task produces a terminal event (merged deltas count 1).
        if events >= n:
            break
        time.sleep(0.05)
    assert events >= n, f"only {events} events ingested"
    assert frames < events, (frames, events)
    assert frames < n, f"{frames} frames for {n} tasks — no batching"
    # The streamed records actually landed: finished lease-path tasks
    # are visible to the state API with their timing fields.
    from ray_tpu.state.api import list_tasks
    done = [r for r in list_tasks()
            if "tick" in (r.get("name") or "")
            and r["state"] == "FINISHED"]
    assert len(done) >= n * 0.9
    assert any(r.get("received_at") for r in done)


# ---------------------------------------------------------------------------
# Flight recorder (bounded wire/scheduler event ring)
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_caps():
    """Bounded ring: capacity honored, evictions counted.  Background
    cluster threads from sibling tests may record concurrently, so
    assertions filter on a private category and use lower bounds."""
    from ray_tpu.util import flight_recorder as fr

    fr.configure(capacity=16, enable=True)
    try:
        for i in range(40):
            fr.record("_test_ring", "e", i=i)
        st = fr.stats()
        assert st["capacity"] == 16
        assert st["events"] == 16
        assert st["dropped"] >= 24
        mine = [e for e in fr.dump() if e["category"] == "_test_ring"]
        assert mine[-1]["i"] == 39  # newest kept
        assert all(e["i"] >= 24 for e in mine)  # the oldest 24 evicted
        assert fr.dump(last=4) == fr.dump()[-4:]
    finally:
        fr.configure(capacity=0, enable=True)  # back to env default


def test_flight_recorder_captures_wire_batches():
    """A coalesced drain round drops a wire/batch_flush event in the
    ring (deterministic via the gated stub sock — the same contention
    setup as test_rpc_batching's sender test)."""
    import pickle as _pickle
    import threading as _threading

    from ray_tpu.core import rpc
    from ray_tpu.util import flight_recorder as fr

    class _GatedSock:
        def __init__(self):
            self.gate = _threading.Event()
            self.sent = _threading.Event()

        def sendall(self, data):
            self.sent.set()
            self.gate.wait()

    fr.clear()
    sock = _GatedSock()
    sender = rpc._CoalescingSender(sock, _threading.Lock())
    t = _threading.Thread(
        target=sender.send,
        args=(rpc.KIND_ONEWAY, 0, _pickle.dumps({"i": 0})))
    t.start()
    assert sock.sent.wait(2.0)
    for i in range(1, 6):
        sender.send(rpc.KIND_ONEWAY, 0, _pickle.dumps({"i": i}))
    sock.gate.set()
    t.join(2.0)
    sender.flush()
    flushes = [e for e in fr.dump()
               if e["category"] == "wire" and e["event"] == "batch_flush"]
    assert any(e["msgs"] == 5 for e in flushes), flushes
    # Timeline surfaces the ring as a dedicated wire lane.
    from ray_tpu.util import timeline as tl
    lanes = {e["pid"] for e in tl.flight_recorder_events()
             if e.get("ph") == "i"}
    assert tl.WIRE_PID in lanes


@pytest.mark.usefixtures("ray_start_regular")
def test_flight_recorder_captures_scheduler_decisions():
    from ray_tpu.util import flight_recorder as fr

    @ray_tpu.remote
    def spark(i):
        return i

    ray_tpu.get([spark.remote(i) for i in range(50)])
    deadline = time.time() + 5
    grants = []
    while time.time() < deadline:
        grants = [e for e in fr.dump()
                  if e["category"] == "scheduler"
                  and e["event"] == "lease_grant"]
        if grants:
            break
        time.sleep(0.05)
    assert grants, "no lease_grant events recorded"
    assert any(e.get("granted", 0) >= 1 for e in grants)
    from ray_tpu.util import timeline as tl
    lanes = {e["pid"] for e in tl.flight_recorder_events()
             if e.get("ph") == "i"}
    assert tl.SCHED_PID in lanes


# ---------------------------------------------------------------------------
# Metrics snapshot freshness (stale-key expiry + clean unpublish)
# ---------------------------------------------------------------------------

def test_aggregate_skips_and_deletes_stale_snapshots():
    import pickle as _pickle

    store = {
        "__metrics__/old": _pickle.dumps({
            "ts": time.time() - 3600,
            "snapshots": [{"name": "zombie_metric", "kind": "counter",
                           "description": "", "series": {(): 1.0}}]}),
        "__metrics__/fresh": _pickle.dumps({
            "ts": time.time(),
            "snapshots": [{"name": "live_metric", "kind": "counter",
                           "description": "", "series": {(): 2.0}}]}),
    }

    def kv_call(msg):
        if msg["op"] == "kv_keys":
            return [k for k in store if k.startswith(msg["prefix"])]
        if msg["op"] == "kv_get":
            return store.get(msg["key"])
        if msg["op"] == "kv_del":
            store.pop(msg["key"], None)
            return True
        raise AssertionError(msg)

    snaps = metrics_mod.aggregate_snapshots(kv_call)
    names = {s["name"] for s in snaps}
    assert "live_metric" in names
    assert "zombie_metric" not in names
    # The stale key was garbage-collected, not just skipped.
    assert "__metrics__/old" not in store
    # skip_ident excludes the caller's own key (it reads itself live).
    assert metrics_mod.aggregate_snapshots(kv_call,
                                           skip_ident="fresh") == []


def test_metrics_ttl_env_knob(monkeypatch):
    monkeypatch.setenv("RAY_TPU_METRICS_TTL_S", "0.05")
    import pickle as _pickle

    store = {"__metrics__/w": _pickle.dumps({
        "ts": time.time() - 1.0,
        "snapshots": [{"name": "m", "kind": "counter",
                       "description": "", "series": {(): 1.0}}]})}

    def kv_call(msg):
        if msg["op"] == "kv_keys":
            return list(store)
        if msg["op"] == "kv_get":
            return store.get(msg["key"])
        if msg["op"] == "kv_del":
            store.pop(msg["key"], None)
            return True

    assert metrics_mod.aggregate_snapshots(kv_call) == []
    assert not store  # expired under the tightened TTL


def test_unpublish_deletes_kv_key(monkeypatch):
    deleted = []

    def kv_call(msg):
        assert msg["op"] == "kv_del"
        deleted.append(msg["key"])
        return True

    # Never published in this state: unpublish is a no-op.
    monkeypatch.setattr(metrics_mod, "_published", False)
    metrics_mod.unpublish(kv_call, "abc")
    assert deleted == []
    monkeypatch.setattr(metrics_mod, "_published", True)
    metrics_mod.unpublish(kv_call, "abc")
    assert deleted == ["__metrics__/abc"]
    assert metrics_mod._published is False


# ---------------------------------------------------------------------------
# Timeline sampling + lane ordering
# ---------------------------------------------------------------------------

def test_timeline_sampling_keeps_first_and_last():
    from ray_tpu.util.timeline import _sample_uniform

    tasks = [{"i": i} for i in range(1000)]
    for cap in (2, 3, 7, 100, 999):
        picked = _sample_uniform(tasks, cap)
        assert len(picked) <= cap
        assert picked[0]["i"] == 0, cap
        assert picked[-1]["i"] == 999, cap
    assert _sample_uniform(tasks, 1) == [tasks[0]]


def test_timeline_lane_sort_indices():
    """The driver scheduling row is pinned first (sort_index -1) and
    trace ids ride the task slices' args."""
    from ray_tpu.util.timeline import DRIVER_PID, timeline_events

    class FakeRuntime:
        @staticmethod
        def state_list(kind):
            assert kind == "tasks"
            return [{"task_id": "t1", "name": "job", "state": "FINISHED",
                     "worker": "w", "pid": 4242, "submitted_at": 1.0,
                     "started_at": 2.0, "finished_at": 3.0,
                     "trace_id": "tr", "span_id": "sp",
                     "parent_span_id": "pa"}]

    events = timeline_events(FakeRuntime(), include_flight=False)
    sort_meta = {e["pid"]: e["args"]["sort_index"] for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "process_sort_index"}
    assert sort_meta[DRIVER_PID] == -1
    task = next(e for e in events
                if e.get("ph") == "X" and e["cat"] == "task")
    assert task["args"]["trace_id"] == "tr"
    assert task["args"]["parent_span_id"] == "pa"
    sched = next(e for e in events
                 if e.get("ph") == "X" and e["cat"] == "scheduling")
    assert sched["pid"] == DRIVER_PID


# ---------------------------------------------------------------------------
# Dashboard: /api/trace + /api/flight_recorder
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("ray_start_regular")
def test_dashboard_trace_and_flight_recorder_endpoints():
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    tracing.clear_spans()
    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def f():
            return 1

        ray_tpu.get(f.remote())
        from ray_tpu.core.runtime import get_runtime
        rt = get_runtime()
        dash = Dashboard(rt)
        try:
            tr = json.loads(urllib.request.urlopen(
                dash.url + "/api/trace").read())
            assert isinstance(tr, list) and tr
            cats = {e.get("cat") for e in tr}
            assert "span" in cats  # driver spans lane present
            fr = json.loads(urllib.request.urlopen(
                dash.url + "/api/flight_recorder").read())
            assert "events" in fr and "stats" in fr
            assert fr["stats"]["capacity"] >= 16
            # Wire counters surfaced on the Prometheus endpoint too.
            text = urllib.request.urlopen(
                dash.url + "/metrics").read().decode()
            assert "rpc_frames_total" in text
        finally:
            dash.stop()
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()


# ---------------------------------------------------------------------------
# Overhead budget (scripts/bench_observability.py writes OBS_BENCH.json)
# ---------------------------------------------------------------------------

def test_observability_overhead_budget():
    bench = os.path.join(os.path.dirname(__file__), os.pardir,
                         "OBS_BENCH.json")
    if not os.path.exists(bench):
        pytest.skip("OBS_BENCH.json not generated")
    with open(bench) as f:
        doc = json.load(f)
    row = doc["multi_client_tasks_async"]
    assert row["disabled_ops_s"] > 0 and row["enabled_ops_s"] > 0
    # The bench's overhead figure is the median of per-round
    # enabled/disabled ratios from interleaved windows — the two
    # medians alone would re-import the machine drift the pairing
    # cancels out.
    overhead = row["overhead"]
    assert overhead < 0.05, (
        f"observability overhead {overhead:.1%} exceeds the 5% budget "
        f"({row['enabled_ops_s']:.0f} vs {row['disabled_ops_s']:.0f} "
        f"ops/s)")


def test_logging_config_structured_workers():
    """ray_tpu.LoggingConfig (counterpart of ray.LoggingConfig,
    _private/ray_logging/): JSON encoding + level apply to the driver
    and propagate to workers via the session environment."""
    import json
    import logging

    import ray_tpu
    from ray_tpu.core.logging_config import JsonFormatter, LoggingConfig

    # Formatter unit: record -> one JSON object with context fields.
    fmt = JsonFormatter(extra_attrs=("lineno",))
    rec = logging.LogRecord("my.logger", logging.WARNING, __file__, 42,
                            "boom %s", ("x",), None)
    obj = json.loads(fmt.format(rec))
    assert obj["levelname"] == "WARNING"
    assert obj["name"] == "my.logger"
    assert obj["message"] == "boom x"
    assert obj["lineno"] == 42

    with pytest.raises(ValueError):
        LoggingConfig(encoding="YAML")

    root = logging.getLogger()
    prev_level = root.level
    prev_formatters = [(h, h.formatter) for h in root.handlers]
    ray_tpu.init(num_cpus=2, log_to_driver=False,
                 logging_config=LoggingConfig(encoding="JSON",
                                              log_level="DEBUG"))
    try:
        assert logging.getLogger().level == logging.DEBUG

        @ray_tpu.remote
        def probe():
            import json as _json
            import logging as _logging
            import os as _os

            root = _logging.getLogger()
            h = root.handlers[0]
            rec = _logging.LogRecord("w", _logging.INFO, "f", 1,
                                     "from-worker", (), None)
            return {
                "level": root.level,
                "formatted": h.formatter.format(rec),
                "env": _os.environ.get("RAY_TPU_LOGGING_CONFIG", ""),
            }

        out = ray_tpu.get(probe.remote(), timeout=120)
        assert out["level"] == logging.DEBUG
        parsed = json.loads(out["formatted"])
        assert parsed["message"] == "from-worker"
        assert parsed.get("worker_id")  # executing-context join key
        assert "JSON" in out["env"]
    finally:
        ray_tpu.shutdown()
        import os

        assert "RAY_TPU_LOGGING_CONFIG" not in os.environ
        root.setLevel(prev_level)  # don't leak DEBUG into later tests
        for h, f in prev_formatters:
            h.setFormatter(f)
