"""Adaptive search tests: TPE, ConcurrencyLimiter, lazy trial creation
(SURVEY.md §2.3 L3 search algorithms)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    TPESearcher,
)


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _make_objective():
    # Defined via closure so cloudpickle ships it by value (a module-level
    # test function would pickle by reference and fail in workers).
    def objective(config):
        import numpy as np

        from ray_tpu import tune

        # Smooth bowl: optimum at x=0.3, y=-0.2, lr=1e-2.
        x, y = config["x"], config["y"]
        lr_err = (np.log10(config["lr"]) + 2.0) ** 2
        loss = (x - 0.3) ** 2 + (y + 0.2) ** 2 + 0.1 * lr_err
        tune.report({"loss": float(loss)})

    return objective


_SPACE = {
    "x": tune.uniform(-1.0, 1.0),
    "y": tune.uniform(-1.0, 1.0),
    "lr": tune.loguniform(1e-4, 1e0),
}


def _best_loss(searcher, num_samples=28, seed=0):
    tuner = tune.Tuner(
        _make_objective(),
        param_space=dict(_SPACE),
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=num_samples,
            search_alg=searcher, max_concurrent_trials=2, seed=seed),
    )
    results = tuner.fit()
    return results.get_best_result(metric="loss", mode="min").metrics[
        "loss"]


def test_tpe_unit_suggestions_move_toward_good_region():
    """Pure-searcher unit check: feed synthetic results; suggestions
    concentrate near the observed optimum."""
    s = TPESearcher(n_initial=8, seed=0)
    s.set_space(dict(_SPACE), "loss", "min")
    rng = np.random.default_rng(0)
    for i in range(30):
        cfg = {"x": float(rng.uniform(-1, 1)),
               "y": float(rng.uniform(-1, 1)),
               "lr": float(10 ** rng.uniform(-4, 0))}
        loss = (cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.2) ** 2
        s.on_trial_complete(f"t{i}", {"loss": loss}, config=cfg)
    xs = [c["x"] for c in s.next_configs(20)]
    ys = [c["y"] for c in s.next_configs(20)]
    # Suggestions cluster around the optimum, far tighter than the
    # uniform prior (std 0.58 over [-1, 1]).
    assert abs(np.mean(xs) - 0.3) < 0.35, np.mean(xs)
    assert abs(np.mean(ys) + 0.2) < 0.35, np.mean(ys)


def test_tpe_finds_lower_loss_than_its_random_phase():
    best = _best_loss(TPESearcher(n_initial=8, seed=1), num_samples=28)
    assert best < 0.08, best


def test_lazy_trial_creation_feeds_searcher_results():
    """Adaptive searchers must see earlier results before later
    suggestions — verified by recording observation counts at suggest
    time."""

    class Recorder(BasicVariantGenerator):
        def __init__(self):
            super().__init__(seed=0)
            self.completed = 0
            self.seen_at_suggest = []

        def next_configs(self, n):
            self.seen_at_suggest.extend([self.completed] * n)
            return super().next_configs(n)

        def on_trial_complete(self, trial_id, result, error=False,
                              config=None):
            self.completed += 1

    rec = Recorder()
    tune.Tuner(
        _make_objective(),
        param_space=dict(_SPACE),
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=8,
            search_alg=rec, max_concurrent_trials=2),
    ).fit()
    assert len(rec.seen_at_suggest) == 8
    # The tail of the experiment was suggested AFTER results landed.
    assert rec.seen_at_suggest[-1] >= 4, rec.seen_at_suggest


def test_concurrency_limiter_caps_inflight():
    inner = BasicVariantGenerator(seed=0)
    lim = ConcurrencyLimiter(inner, max_concurrent=2)
    lim.set_space(dict(_SPACE), "loss", "min")
    first = lim.next_configs(5)
    assert len(first) == 2  # capped
    assert lim.next_configs(1) == []  # saturated
    lim.on_trial_complete("a", {"loss": 1.0}, config=first[0])
    assert len(lim.next_configs(5)) == 1  # one slot released
