"""Adaptive search tests: TPE, ConcurrencyLimiter, lazy trial creation
(SURVEY.md §2.3 L3 search algorithms)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    TPESearcher,
)


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _make_objective():
    # Defined via closure so cloudpickle ships it by value (a module-level
    # test function would pickle by reference and fail in workers).
    def objective(config):
        import numpy as np

        from ray_tpu import tune

        # Smooth bowl: optimum at x=0.3, y=-0.2, lr=1e-2.
        x, y = config["x"], config["y"]
        lr_err = (np.log10(config["lr"]) + 2.0) ** 2
        loss = (x - 0.3) ** 2 + (y + 0.2) ** 2 + 0.1 * lr_err
        tune.report({"loss": float(loss)})

    return objective


_SPACE = {
    "x": tune.uniform(-1.0, 1.0),
    "y": tune.uniform(-1.0, 1.0),
    "lr": tune.loguniform(1e-4, 1e0),
}


def _best_loss(searcher, num_samples=28, seed=0):
    tuner = tune.Tuner(
        _make_objective(),
        param_space=dict(_SPACE),
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=num_samples,
            search_alg=searcher, max_concurrent_trials=2, seed=seed),
    )
    results = tuner.fit()
    return results.get_best_result(metric="loss", mode="min").metrics[
        "loss"]


def test_tpe_unit_suggestions_move_toward_good_region():
    """Pure-searcher unit check: feed synthetic results; suggestions
    concentrate near the observed optimum."""
    s = TPESearcher(n_initial=8, seed=0)
    s.set_space(dict(_SPACE), "loss", "min")
    rng = np.random.default_rng(0)
    for i in range(30):
        cfg = {"x": float(rng.uniform(-1, 1)),
               "y": float(rng.uniform(-1, 1)),
               "lr": float(10 ** rng.uniform(-4, 0))}
        loss = (cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.2) ** 2
        s.on_trial_complete(f"t{i}", {"loss": loss}, config=cfg)
    xs = [c["x"] for c in s.next_configs(20)]
    ys = [c["y"] for c in s.next_configs(20)]
    # Suggestions cluster around the optimum, far tighter than the
    # uniform prior (std 0.58 over [-1, 1]).
    assert abs(np.mean(xs) - 0.3) < 0.35, np.mean(xs)
    assert abs(np.mean(ys) + 0.2) < 0.35, np.mean(ys)


def test_tpe_finds_lower_loss_than_its_random_phase():
    best = _best_loss(TPESearcher(n_initial=8, seed=1), num_samples=28)
    assert best < 0.08, best


def test_lazy_trial_creation_feeds_searcher_results():
    """Adaptive searchers must see earlier results before later
    suggestions — verified by recording observation counts at suggest
    time."""

    class Recorder(BasicVariantGenerator):
        def __init__(self):
            super().__init__(seed=0)
            self.completed = 0
            self.seen_at_suggest = []

        def next_configs(self, n):
            self.seen_at_suggest.extend([self.completed] * n)
            return super().next_configs(n)

        def on_trial_complete(self, trial_id, result, error=False,
                              config=None):
            self.completed += 1

    rec = Recorder()
    tune.Tuner(
        _make_objective(),
        param_space=dict(_SPACE),
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=8,
            search_alg=rec, max_concurrent_trials=2),
    ).fit()
    assert len(rec.seen_at_suggest) == 8
    # The tail of the experiment was suggested AFTER results landed.
    assert rec.seen_at_suggest[-1] >= 4, rec.seen_at_suggest


def test_concurrency_limiter_caps_inflight():
    inner = BasicVariantGenerator(seed=0)
    lim = ConcurrencyLimiter(inner, max_concurrent=2)
    lim.set_space(dict(_SPACE), "loss", "min")
    first = lim.next_configs(5)
    assert len(first) == 2  # capped
    assert lim.next_configs(1) == []  # saturated
    lim.on_trial_complete("a", {"loss": 1.0}, config=first[0])
    assert len(lim.next_configs(5)) == 1  # one slot released


# ---------------------------------------------------------------------------
# ask/tell Searcher protocol + Optuna adapter + PB2 (round 3:
# reference searcher.py / optuna_search.py / schedulers/pb2.py)
# ---------------------------------------------------------------------------

import math
import types

from ray_tpu.tune.pb2 import PB2, _GP
from ray_tpu.tune.searchers import (
    OptunaSearch,
    Searcher,
    as_search_algorithm,
)


class CountingSearcher(Searcher):
    """Deterministic ask/tell searcher: suggests x = n."""

    def __init__(self):
        self.n = 0
        self.told = []

    def suggest(self, trial_id):
        self.n += 1
        return {"x": self.n}

    def on_trial_complete(self, trial_id, result=None, error=False):
        self.told.append((trial_id, result, error))


def test_adapter_suggest_and_tell_roundtrip():
    s = CountingSearcher()
    alg = as_search_algorithm(s)
    alg.set_space({}, "score", "max")
    cfgs = alg.next_configs(3)
    assert [c["x"] for c in cfgs] == [1, 2, 3]
    alg.on_trial_complete("t0", {"score": 5.0}, config=cfgs[1])
    assert len(s.told) == 1
    tid, result, error = s.told[0]
    assert result == {"score": 5.0} and not error
    assert tid == cfgs[1]["__searcher_trial_id__"]


def test_adapter_end_to_end_with_tuner():
    searcher = CountingSearcher()

    def objective(config):
        tune.report({"score": config["x"] * 2.0})

    results = tune.Tuner(
        objective,
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=4,
            search_alg=as_search_algorithm(searcher)),
    ).fit()
    assert results.get_best_result(
        metric="score", mode="max").metrics["score"] == 8.0
    assert len(searcher.told) == 4


def _stub_optuna():
    """Minimal ask/tell optuna lookalike (image is offline)."""
    rng = np.random.default_rng(0)

    class _Trial:
        def suggest_float(self, name, lo, hi, log=False, step=None):
            v = (math.exp(rng.uniform(math.log(lo), math.log(hi)))
                 if log else float(rng.uniform(lo, hi)))
            return round(v / step) * step if step else v

        def suggest_int(self, name, lo, hi, log=False):
            return int(rng.integers(lo, hi + 1))

        def suggest_categorical(self, name, values):
            return values[int(rng.integers(0, len(values)))]

    class _Study:
        def __init__(self):
            self.told = []

        def ask(self):
            return _Trial()

        def tell(self, trial, value=None, state=None):
            self.told.append((trial, value, state))

    stub = types.SimpleNamespace()
    stub.create_study = lambda direction=None, sampler=None: _Study()
    stub.trial = types.SimpleNamespace(TrialState=None)
    return stub


def test_optuna_adapter_with_stub():
    s = OptunaSearch(_optuna_module=_stub_optuna())
    s.set_search_properties("loss", "min", {
        "lr": tune.loguniform(1e-5, 1e-1),
        "layers": tune.randint(1, 5),
        "act": tune.choice(["relu", "gelu"]),
        "fixed": 7,
    })
    cfg = s.suggest("t1")
    assert 1e-5 <= cfg["lr"] <= 1e-1
    assert 1 <= cfg["layers"] <= 4
    assert cfg["act"] in ("relu", "gelu")
    assert cfg["fixed"] == 7
    s.on_trial_complete("t1", {"loss": 0.3})
    assert s._study.told[0][1] == 0.3


def test_optuna_missing_raises_with_guidance():
    with pytest.raises(ImportError, match="TPESearcher"):
        OptunaSearch()


def test_gp_fits_and_predicts():
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(30, 2))
    y = np.sin(3 * x[:, 0]) + 0.1 * x[:, 1]
    gp = _GP(x, y)
    mu, sd = gp.predict(x[:5])
    assert np.allclose(mu, y[:5], atol=0.2)
    assert (sd >= 0).all()


class _FakeTrial:
    def __init__(self, tid, config):
        self.trial_id = tid
        self.config = config
        self.exploit_directive = None


def test_pb2_exploit_suggests_within_bounds():
    pb2 = PB2(perturbation_interval=2,
              hyperparam_bounds={"lr": (1e-4, 1e-1)}, seed=0)
    pb2.set_objective("score", "max")
    trials = [_FakeTrial(f"t{i}", {"lr": lr})
              for i, lr in enumerate([1e-4, 1e-3, 1e-2, 1e-1])]
    for step in range(1, 7):
        for i, tr in enumerate(trials):
            # higher lr -> bigger score gains (monotone signal)
            pb2.on_trial_result(
                tr, {"training_iteration": step,
                     "score": step * (i + 1) * 0.1})
    directives = [t.exploit_directive for t in trials
                  if t.exploit_directive is not None]
    assert directives, "bottom-quantile trial never exploited"
    for d in directives:
        assert 1e-4 <= d["config"]["lr"] <= 1e-1
        assert d["donor"] in {t.trial_id for t in trials}


def test_pb2_end_to_end_learns():
    """PB2-driven tuning of a 1-d quadratic: exploited configs stay in
    bounds and the experiment improves on the cold start."""

    def objective(config):
        x = config["x"]
        for i in range(6):
            tune.report({"score": -(x - 0.7) ** 2 + 0.01 * i})

    pb2 = PB2(perturbation_interval=2,
              hyperparam_bounds={"x": (0.0, 1.0)}, seed=1)
    results = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=4, scheduler=pb2),
    ).fit()
    best = results.get_best_result(
        metric="score", mode="max").metrics["score"]
    assert best > -0.5


def test_bohb_conditions_on_largest_adequate_budget():
    """BOHB model selection (Falkner et al. 2018): proposals use the
    LARGEST budget with enough completed observations; low-budget
    observations only fill in before any budget qualifies."""
    from ray_tpu.tune.search import BOHBSearcher, uniform

    s = BOHBSearcher(n_initial=4, seed=0)
    s.set_space({"x": uniform(0.0, 1.0)}, metric="score", mode="max")

    # 6 completions at budget 1 (good x near 0.9), 4 at budget 9
    # (good x near 0.1 — the higher fidelity disagrees on purpose).
    for i in range(6):
        x = 0.9 + 0.01 * i
        s.on_trial_complete(f"a{i}", {"score": 1 - abs(x - 0.9),
                                      "training_iteration": 1},
                            config={"x": x})
    assert s._model_budget() == 1.0
    for i in range(4):
        x = 0.1 + 0.01 * i
        s.on_trial_complete(f"b{i}", {"score": 1 - abs(x - 0.1),
                                      "training_iteration": 9},
                            config={"x": x})
    assert s._model_budget() == 9.0  # switched to the higher fidelity

    xs = [c["x"] for c in s.next_configs(20)]
    # Proposals must follow the high-budget model (cluster near 0.1).
    assert sum(1 for x in xs if x < 0.5) >= 15, xs


def test_bohb_with_hyperband_end_to_end():
    """BOHB + HyperBand pairing over a real Tuner run: rung stops give
    completed trials at multiple budgets, and the searcher's model picks
    up the signal."""
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.tune.schedulers import HyperBandScheduler
    from ray_tpu.tune.search import BOHBSearcher

    ray_tpu.init(num_cpus=4, log_to_driver=False)
    try:
        def objective(config):
            for it in range(9):
                tune.report({"score": 1.0 - (config["x"] - 0.7) ** 2
                             + 0.01 * it})

        results = tune.Tuner(
            objective,
            param_space={"x": tune.uniform(0.0, 1.0)},
            tune_config=tune.TuneConfig(
                metric="score", mode="max", num_samples=16,
                max_concurrent_trials=4, seed=3,
                search_alg=BOHBSearcher(n_initial=4, seed=3),
                scheduler=HyperBandScheduler(max_t=9,
                                             reduction_factor=3)),
        ).fit()
        best = results.get_best_result()
        assert best.metrics["score"] > 0.8
        budgets = {float(r.metrics.get("training_iteration", 0))
                   for r in results}
        assert len(budgets) > 1, budgets  # rung stops -> multi-fidelity
    finally:
        ray_tpu.shutdown()


def test_bohb_budget_binning():
    """Integral budgets key exactly; continuous ones coalesce (a raw
    float time_total_s key would make every bucket a singleton); a
    budget of 0 is kept, not rebinned by truthiness."""
    from ray_tpu.tune.search import BOHBSearcher, uniform

    s = BOHBSearcher(n_initial=2, time_attr="time_total_s", seed=0)
    s.set_space({"x": uniform(0.0, 1.0)}, metric="m", mode="max")
    for i, t in enumerate([60.12, 60.33, 59.8, 61.0]):
        s.on_trial_complete(f"t{i}", {"m": 0.5, "time_total_s": t},
                            config={"x": 0.5})
    assert len(s._obs_by_budget) <= 2  # coalesced, not 4 singletons
    assert s._model_budget() is not None

    assert BOHBSearcher._budget_bin(0.0) == 0.0
    assert BOHBSearcher._budget_bin(9.0) == 9.0
    s2 = BOHBSearcher(n_initial=2, seed=0)
    s2.set_space({"x": uniform(0.0, 1.0)}, metric="m", mode="max")
    s2.on_trial_complete("z", {"m": 1.0, "training_iteration": 0},
                         config={"x": 0.1})
    assert 0.0 in s2._obs_by_budget  # not merged into budget 1


# ---------------------------------------------------------------------------
# External searcher adapters (external_searchers.py): Ax / Nevergrad /
# HEBO / ZOOpt, exercised against protocol-faithful stubs (the real
# packages are not in the air-gapped image; where they exist the same
# adapter code activates unchanged).

def _ext_space():
    return {
        "lr": tune.loguniform(1e-5, 1e-1),
        "layers": tune.randint(1, 5),
        "act": tune.choice(["relu", "gelu"]),
        "opt": tune.grid_search(["sgd", "adam"]),
        "fixed": 7,
    }


def _check_cfg(cfg):
    assert 1e-5 <= cfg["lr"] <= 1e-1
    assert 1 <= cfg["layers"] <= 4 and isinstance(cfg["layers"], int)
    assert cfg["act"] in ("relu", "gelu")
    # grid_search leaves are categoricals to external optimizers.
    assert cfg["opt"] in ("sgd", "adam")
    assert cfg["fixed"] == 7


def test_ax_adapter_with_stub():
    import random

    from ray_tpu.tune import AxSearch

    class _AxClient:
        def __init__(self):
            self.completed = []
            self._rng = random.Random(0)
            self._n = 0

        def create_experiment(self, name, parameters, objective_name,
                              minimize):
            self.params = parameters
            self.minimize = minimize

        def get_next_trial(self):
            out = {}
            for p in self.params:
                if p["type"] == "choice":
                    out[p["name"]] = self._rng.choice(p["values"])
                else:
                    lo, hi = p["bounds"]
                    v = self._rng.uniform(lo, hi)
                    out[p["name"]] = int(v) if p["value_type"] == "int" \
                        else v
            self._n += 1
            return out, self._n

        def complete_trial(self, index, raw_data):
            self.completed.append((index, raw_data))

    client = _AxClient()
    s = AxSearch(ax_client=client)
    s.set_search_properties("loss", "min", _ext_space())
    assert client.minimize
    cfg = s.suggest("t1")
    _check_cfg(cfg)
    s.on_trial_complete("t1", {"loss": 0.5})
    assert client.completed[0][1] == {"loss": (0.5, 0.0)}


def test_nevergrad_adapter_with_stub():
    import random
    import types

    from ray_tpu.tune import NevergradSearch

    rng = random.Random(0)

    class _Inst:
        def __init__(self, sample):
            self._sample = sample

        def set_integer_casting(self):
            s = self._sample
            self._sample = lambda: int(s())
            return self

    class _Cand:
        def __init__(self, value):
            self.value = value

    class _Opt:
        def __init__(self, parametrization=None, budget=None):
            self.param = parametrization
            self.told = []

        def ask(self):
            return _Cand({k: v._sample()
                          for k, v in self.param.insts.items()})

        def tell(self, cand, loss):
            self.told.append((cand, loss))

    class _Dict:
        def __init__(self, **insts):
            self.insts = insts

    ng = types.SimpleNamespace(
        p=types.SimpleNamespace(
            Scalar=lambda lower, upper: _Inst(
                lambda: rng.uniform(lower, upper)),
            Log=lambda lower, upper: _Inst(
                lambda: lower * (upper / lower) ** rng.random()),
            Choice=lambda values: _Inst(lambda: rng.choice(values)),
            Dict=_Dict),
        optimizers=types.SimpleNamespace(NGOpt=_Opt))

    s = NevergradSearch(_module=ng)
    s.set_search_properties("score", "max", _ext_space())
    cfg = s.suggest("t1")
    _check_cfg(cfg)
    s.on_trial_complete("t1", {"score": 2.0})
    assert s._opt.told[0][1] == -2.0  # max -> negated for a minimizer


def test_hebo_adapter_with_stub():
    import random

    import numpy as np

    from ray_tpu.tune import HEBOSearch

    rng = random.Random(0)

    class _Frame:
        """Tiny stand-in for the pandas DataFrame HEBO returns."""

        def __init__(self, row):
            self._row = row
            self.iloc = [types.SimpleNamespace(to_dict=lambda r=row: r)]

    class _Hebo:
        def __init__(self, space):
            self.space = space
            self.observed = []

        def suggest(self, n_suggestions=1):
            row = {}
            for spec in self.space.specs:
                if spec["type"] == "cat":
                    row[spec["name"]] = rng.choice(spec["categories"])
                elif spec["type"] == "int":
                    row[spec["name"]] = rng.randint(spec["lb"],
                                                    spec["ub"])
                elif spec["type"] == "pow":
                    row[spec["name"]] = spec["lb"] * (
                        spec["ub"] / spec["lb"]) ** rng.random()
                else:
                    row[spec["name"]] = rng.uniform(spec["lb"],
                                                    spec["ub"])
            return _Frame(row)

        def observe(self, rec, y):
            self.observed.append((rec, np.asarray(y)))

    class _Space:
        def parse(self, specs):
            self.specs = specs
            return self

    import types

    s = HEBOSearch(_module=(_Hebo, _Space))
    s.set_search_properties("score", "max", _ext_space())
    cfg = s.suggest("t1")
    _check_cfg(cfg)
    s.on_trial_complete("t1", {"score": 3.0})
    rec, y = s._opt.observed[0]
    assert y[0][0] == -3.0  # max -> negated for a minimizer


def test_zoopt_adapter_with_stub():
    import random
    import types

    from ray_tpu.tune import ZOOptSearch

    rng = random.Random(0)

    class _Solution:
        def __init__(self, xs):
            self._xs = xs

        def get_x(self):
            return self._xs

    class _Dimension:
        def __init__(self, n, ranges, continuous):
            self.n, self.ranges, self.continuous = n, ranges, continuous

    class _Objective:
        def __init__(self, fn, dim):
            self.fn, self.dim = fn, dim

    class _Opt:
        """Solve loop: samples uniformly, calls the (blocking)
        objective — the adapter inverts this into ask/tell."""

        @staticmethod
        def min(obj, par):
            for _ in range(par.budget):
                xs = []
                for (lo, hi), cont in zip(obj.dim.ranges,
                                          obj.dim.continuous):
                    v = rng.uniform(lo, hi)
                    xs.append(v if cont else int(round(v)))
                obj.fn(_Solution(xs))

    z = types.SimpleNamespace(
        Dimension=_Dimension, Objective=_Objective,
        Parameter=lambda budget: types.SimpleNamespace(budget=budget),
        Opt=_Opt)

    s = ZOOptSearch(budget=4, _module=z)
    s.set_search_properties("loss", "min", _ext_space())
    for i in range(3):
        cfg = s.suggest(f"t{i}")
        _check_cfg(cfg)
        s.on_trial_complete(f"t{i}", {"loss": 1.0 - 0.1 * i})
    # Every reported value reached the solve thread.
    assert s._next_ask >= 3


def test_external_adapters_missing_raise_with_guidance():
    from ray_tpu.tune import (
        AxSearch,
        HEBOSearch,
        NevergradSearch,
        ZOOptSearch,
    )

    for cls, mod, hint in (
            (AxSearch, "ax", "PB2"),
            (NevergradSearch, "nevergrad", "TPE"),
            (HEBOSearch, "hebo", "PB2"),
            (ZOOptSearch, "zoopt", "TPE")):
        try:
            __import__(mod)
        except ImportError:
            pass
        else:
            continue  # library present: the adapter activates instead
        with pytest.raises(ImportError, match=hint):
            cls()


def test_hyperopt_adapter_with_stub():
    """Protocol-faithful hyperopt stub: Trials doc store,
    algo(new_ids, domain, trials, seed) -> trial docs, completion by
    in-place doc mutation + refresh (the real library's surface)."""
    import math
    import random
    import types

    from ray_tpu.tune import HyperOptSearch

    rng = random.Random(0)

    class _Trials:
        def __init__(self):
            self.trials = []
            self._next = 0
            self.refreshed = 0

        def new_trial_ids(self, n):
            out = list(range(self._next, self._next + n))
            self._next += n
            return out

        def refresh(self):
            self.refreshed += 1

        def insert_trial_docs(self, docs):
            # Real hyperopt stores SONify'd DEEP COPIES — mutating the
            # caller's doc after insert must not reach the store.
            import copy

            self.trials.extend(copy.deepcopy(docs))

    class _Domain:
        def __init__(self, fn, expr):
            self.fn, self.expr = fn, expr

    def _suggest(new_ids, domain, trials, seed):
        docs = []
        for tid in new_ids:
            vals = {}
            for name, dim in domain.expr.items():
                kind, args = dim
                if kind == "choice":
                    vals[name] = rng.randrange(len(args[0]))
                elif kind == "loguniform":
                    lo, hi = args
                    vals[name] = math.exp(rng.uniform(lo, hi))
                elif kind == "quniform":
                    lo, hi, q = args
                    vals[name] = round(rng.uniform(lo, hi) / q) * q
                elif kind == "qloguniform":
                    lo, hi, q = args
                    vals[name] = round(
                        math.exp(rng.uniform(lo, hi)) / q) * q
                elif kind == "normal":
                    mu, sd = args
                    vals[name] = rng.gauss(mu, sd)
                else:
                    lo, hi = args
                    vals[name] = rng.uniform(lo, hi)
            docs.append({"tid": tid, "state": 0,
                         "misc": {"vals": {k: [v]
                                           for k, v in vals.items()}},
                         "result": None})
        return docs

    hp = types.SimpleNamespace(
        choice=lambda name, opts: ("choice", (opts,)),
        uniform=lambda name, lo, hi: ("uniform", (lo, hi)),
        loguniform=lambda name, lo, hi: ("loguniform", (lo, hi)),
        quniform=lambda name, lo, hi, q: ("quniform", (lo, hi, q)),
        qloguniform=lambda name, lo, hi, q: ("qloguniform",
                                             (lo, hi, q)),
        normal=lambda name, mu, sd: ("normal", (mu, sd)),
    )
    base = types.SimpleNamespace(
        JOB_STATE_DONE=2, JOB_STATE_ERROR=3,
        spec_from_misc=lambda misc: {k: v[0]
                                     for k, v in misc["vals"].items()},
    )
    stub = types.SimpleNamespace(
        hp=hp, base=base, Trials=_Trials, Domain=_Domain,
        tpe=types.SimpleNamespace(suggest=_suggest))

    s = HyperOptSearch(_module=stub)
    s.set_search_properties("score", "max", _ext_space())
    cfg = s.suggest("t1")
    _check_cfg(cfg)
    s.on_trial_complete("t1", {"score": 3.0})
    doc = s._store.trials[0]
    assert doc["state"] == 2
    assert doc["result"] == {"loss": -3.0, "status": "ok"}

    cfg2 = s.suggest("t2")
    _check_cfg(cfg2)
    s.on_trial_complete("t2", error=True)
    assert s._store.trials[1]["state"] == 3
    assert not s._live
