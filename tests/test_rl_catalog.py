"""Catalog + recurrent-module tests: the model decision tree from
(spaces, model_config) to module specs, custom-catalog injection, LSTM
PPO on a memory env, and the Atari-scale pixel pipeline (SURVEY.md §2.3
L5; reference rllib/core/models/catalog.py, rnn_sequencing, and the
tuned Atari examples)."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rl import MODEL_DEFAULTS, Catalog
from ray_tpu.rl import module as rl_module
from ray_tpu.rl.algorithms import PPOConfig
from ray_tpu.rl.envs import BrightQuadrantEnv, RecallEnv
from ray_tpu.rl.module import (
    ConvRLModuleSpec,
    RecurrentRLModuleSpec,
    RLModuleSpec,
)


# ---------------------------------------------------------------------------
# Decision tree
# ---------------------------------------------------------------------------


def test_catalog_decision_tree():
    box4 = gym.spaces.Box(-1.0, 1.0, (4,), np.float32)
    pix = gym.spaces.Box(0.0, 1.0, (84, 84, 4), np.float32)
    disc = gym.spaces.Discrete(3)
    cont = gym.spaces.Box(-2.0, 2.0, (2,), np.float32)

    spec = Catalog(box4, disc, {}).build_module_spec()
    assert type(spec) is RLModuleSpec
    assert spec.hidden_sizes == tuple(MODEL_DEFAULTS["fcnet_hiddens"])
    assert spec.discrete and spec.action_dim == 3

    spec = Catalog(box4, cont, {"fcnet_hiddens": [32, 16],
                                "fcnet_activation": "relu"}
                   ).build_module_spec()
    assert spec.hidden_sizes == (32, 16) and spec.activation == "relu"
    assert not spec.discrete and spec.dist_inputs_dim == 4

    spec = Catalog(pix, disc, {}).build_module_spec()
    assert type(spec) is ConvRLModuleSpec
    assert spec.obs_shape == (84, 84, 4)
    assert spec.conv_filters == ((32, 8, 4), (64, 4, 2), (64, 3, 1))

    small = gym.spaces.Box(0.0, 1.0, (10, 10, 1), np.float32)
    assert Catalog(small, disc, {}).build_module_spec().conv_filters == \
        ((16, 4, 2), (32, 4, 2))

    spec = Catalog(box4, disc, {"use_lstm": True, "lstm_cell_size": 32,
                                "max_seq_len": 8}).build_module_spec()
    assert type(spec) is RecurrentRLModuleSpec
    assert spec.cell_size == 32 and spec.max_seq_len == 8

    with pytest.raises(ValueError, match="unknown model_config"):
        Catalog(box4, disc, {"fcnet_hidden": [32]})
    with pytest.raises(ValueError, match="fcnet_activation"):
        Catalog(box4, disc, {"fcnet_activation": "gelu"})
    # Explicit keys the chosen family cannot apply are rejected, not
    # silently dropped (same contract as DQN/SAC's _q_hiddens).
    with pytest.raises(ValueError, match="conv_filters"):
        Catalog(box4, disc,
                {"conv_filters": [[32, 8, 4]]}).build_module_spec()
    with pytest.raises(ValueError, match="lstm_cell_size"):
        Catalog(box4, disc, {"lstm_cell_size": 64}).build_module_spec()
    # ...but spelling out DEFAULT values requests nothing and is fine.
    spec = Catalog(box4, disc, {"conv_filters": None,
                                "lstm_cell_size": 256}
                   ).build_module_spec()
    assert type(spec) is RLModuleSpec


def test_custom_catalog_subclass_hooks():
    class TinyCatalog(Catalog):
        def _determine_spec_class(self):
            return RLModuleSpec  # force MLP even for pixel obs

        def build_module_spec(self):
            spec = super().build_module_spec()
            import dataclasses

            return dataclasses.replace(spec, hidden_sizes=(8,))

    pix = gym.spaces.Box(0.0, 1.0, (6, 6, 1), np.float32)
    spec = TinyCatalog(pix, gym.spaces.Discrete(2), {}).build_module_spec()
    assert type(spec) is RLModuleSpec and spec.hidden_sizes == (8,)


# ---------------------------------------------------------------------------
# Recurrent module math
# ---------------------------------------------------------------------------


def test_recurrent_act_matches_forward_seq():
    """Step-by-step stateful acting and the scanned training forward
    produce identical values/dist inputs on the same trajectory."""
    spec = RecurrentRLModuleSpec(obs_dim=3, action_dim=2, discrete=True,
                                 hidden_sizes=(8,), cell_size=4,
                                 max_seq_len=8)
    params = spec.init(jax.random.key(0))
    B, T = 2, 5
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.standard_normal((B, T, 3)), jnp.float32)
    isf = np.zeros((B, T), np.float32)
    isf[:, 0] = 1.0
    isf[1, 3] = 1.0  # mid-sequence episode boundary in row 1
    di_seq, v_seq = spec.forward_seq(params, obs, jnp.asarray(isf))

    state = spec.init_runner_state(B)
    key = jax.random.key(1)
    for t in range(T):
        _, _, value, state = spec.act_stateful(
            params, state, obs[:, t], key, jnp.asarray(False),
            jnp.asarray(isf[:, t] > 0))
        np.testing.assert_allclose(np.asarray(value),
                                   np.asarray(v_seq[:, t]),
                                   rtol=1e-5, atol=1e-6)


def test_recurrent_state_reset_isolates_episodes():
    """is_first must zero exactly the flagged rows' state."""
    spec = RecurrentRLModuleSpec(obs_dim=2, action_dim=2, discrete=True,
                                 hidden_sizes=(4,), cell_size=3)
    params = spec.init(jax.random.key(0))
    obs = jnp.ones((2, 2), jnp.float32)
    state = {"h": jnp.full((2, 3), 5.0), "c": jnp.full((2, 3), 5.0)}
    key = jax.random.key(0)
    _, _, _, s_reset = spec.act_stateful(
        params, state, obs, key, jnp.asarray(False),
        jnp.asarray([True, False]))
    _, _, _, s_zero = spec.act_stateful(
        params, spec.init_runner_state(2), obs, key, jnp.asarray(False),
        jnp.asarray([False, False]))
    # Row 0 behaved as if its state were zeros; row 1 kept history.
    np.testing.assert_allclose(np.asarray(s_reset["h"][0]),
                               np.asarray(s_zero["h"][0]), rtol=1e-6)
    assert not np.allclose(np.asarray(s_reset["h"][1]),
                           np.asarray(s_zero["h"][1]))


# ---------------------------------------------------------------------------
# End-to-end learning
# ---------------------------------------------------------------------------


def test_lstm_ppo_learns_memory_task():
    """The catalog's use_lstm path beats the memoryless ceiling on
    RecallEnv: expected return is 0.5 for ANY memoryless policy, so
    crossing 0.8 proves the cue is carried through the LSTM state in
    both rollout (act_stateful) and training (forward_seq)."""
    config = (PPOConfig()
              .environment(env_fn=lambda: RecallEnv(length=4))
              .env_runners(num_envs_per_env_runner=8)
              .rl_module(model_config={"use_lstm": True,
                                       "lstm_cell_size": 32,
                                       "fcnet_hiddens": [32],
                                       "max_seq_len": 8})
              .training(train_batch_size=512, minibatch_size=256,
                        lr=3e-3, num_epochs=6, entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    assert isinstance(algo.env_runner_group.spec, RecurrentRLModuleSpec)
    best = 0.0
    for _ in range(20):
        r = algo.step()
        best = max(best, r.get("episode_return_mean", 0.0))
        if best > 0.8:
            break
    algo.stop()
    assert best > 0.8, best


def test_sequence_batcher_trains_on_every_sampled_step():
    """Short episodes make segments carry fewer than max_seq_len real
    steps; the sequence batcher must still train on ALL of them (a
    train_batch_size // T segment budget would silently discard half
    the rollout here)."""
    config = (PPOConfig()
              .environment(env_fn=lambda: RecallEnv(length=4))
              .env_runners(num_envs_per_env_runner=4)
              .rl_module(model_config={"use_lstm": True,
                                       "lstm_cell_size": 8,
                                       "fcnet_hiddens": [8],
                                       "max_seq_len": 8})
              .training(train_batch_size=256, minibatch_size=128,
                        num_epochs=1)
              .debugging(seed=0))
    algo = config.build()
    r = algo.step()
    algo.stop()
    assert r["num_env_steps_trained"] >= 256, r


def test_conv_heads_honor_activation():
    """fcnet_activation reaches the conv module's MLP heads (a tanh/relu
    mismatch changes outputs)."""
    pix = gym.spaces.Box(0.0, 1.0, (8, 8, 1), np.float32)
    disc = gym.spaces.Discrete(2)
    tanh_spec = Catalog(pix, disc, {"fcnet_activation": "tanh"}
                        ).build_module_spec()
    relu_spec = Catalog(pix, disc, {"fcnet_activation": "relu"}
                        ).build_module_spec()
    params = tanh_spec.init(jax.random.key(0))
    obs = jnp.asarray(
        np.random.default_rng(0).uniform(size=(2, 64)), jnp.float32)
    out_t, _ = tanh_spec.forward(params, obs)
    out_r, _ = relu_spec.forward(params, obs)
    assert not np.allclose(np.asarray(out_t), np.asarray(out_r))


def test_dqn_pixel_env_uses_conv_q_network():
    """DQN auto-selects the conv Q-network for 3-D obs (the reference's
    Atari DQN path) and trains with finite TD loss."""
    from ray_tpu.rl import ConvQNetworkSpec
    from ray_tpu.rl.algorithms import DQNConfig

    config = (DQNConfig()
              .environment(env_fn=lambda: BrightQuadrantEnv(size=10,
                                                            length=8))
              .training(num_steps_sampled_before_learning_starts=64,
                        rollout_fragment_length=64, train_batch_size=32)
              .debugging(seed=0))
    algo = config.build()
    spec = algo.env_runner_group.spec
    assert isinstance(spec, ConvQNetworkSpec)
    assert spec.obs_shape == (10, 10, 1)
    assert spec.conv_filters == ((16, 4, 2), (32, 4, 2))
    r = {}
    for _ in range(3):
        r = algo.step()
    algo.stop()
    assert np.isfinite(r["total_loss"])


def test_dqn_sac_rl_module_config():
    """DQN honors rl_module fcnet_hiddens and rejects keys its module
    can't apply (silent drops would lie about the architecture)."""
    from ray_tpu.rl.algorithms import DQNConfig

    config = (DQNConfig().environment("CartPole-v1")
              .rl_module(model_config={"fcnet_hiddens": [19]})
              .training(num_steps_sampled_before_learning_starts=10_000))
    algo = config.build()
    assert algo.env_runner_group.spec.hidden_sizes == (19,)
    algo.stop()

    bad = (DQNConfig().environment("CartPole-v1")
           .rl_module(model_config={"use_lstm": True}))
    with pytest.raises(ValueError, match="module_spec"):
        bad.build()


def test_recurrent_behavior_target_logp_parity():
    """Under UNCHANGED params, logp/values recomputed on the training
    segments (seeded with the runner's recorded entering states) equal
    the rollout's behavior logp/values exactly — episodes longer than
    max_seq_len included.  This is the property that keeps PPO ratios
    at 1 and V-trace rho free of state artifacts (the reference's
    state_in column)."""
    import gymnasium as gym

    from ray_tpu.rl import SingleAgentEnvRunner
    from ray_tpu.rl.algorithms.ppo import compute_gae
    from ray_tpu.rl.sequences import segment_rows, stack_segments

    spec = RecurrentRLModuleSpec(obs_dim=4, action_dim=2, discrete=True,
                                 hidden_sizes=(16,), cell_size=8,
                                 max_seq_len=5)  # episodes run longer
    runner = SingleAgentEnvRunner(
        lambda: gym.make("CartPole-v1"), num_envs=2, spec=spec, seed=0)
    episodes = runner.sample(num_env_steps=60)
    assert any(len(e) > 5 for e in episodes), "need multi-segment eps"
    params = runner.params
    rows = compute_gae(episodes, params, 0.99, 0.95, spec=spec)
    segs = segment_rows(rows, 5)
    assert "h0" in segs[0]  # recorded-state seeding active
    batch = stack_segments(segs, 1 << (len(segs) - 1).bit_length())

    from ray_tpu.rl.algorithms.ppo import PPOLearner

    learner = PPOLearner(spec, seed=0)
    di, values, flat = learner.forward_flat(
        params, {k: jnp.asarray(v) for k, v in batch.items()})
    logp = np.asarray(spec.dist(di).logp(flat["actions"]))
    mask = np.asarray(flat["mask"]) > 0
    np.testing.assert_allclose(logp[mask],
                               np.asarray(flat["logp"])[mask],
                               rtol=1e-4, atol=1e-5)
    runner.stop()


def test_lstm_appo_learns_memory_task():
    """Recurrent training is not PPO-only: APPO (IMPALA machinery +
    surrogate clipping) trains the catalog's LSTM module through
    V-trace sequence batches and beats the 0.5 memoryless ceiling."""
    from ray_tpu.rl.algorithms import APPOConfig

    config = (APPOConfig()
              .environment(env_fn=lambda: RecallEnv(length=4))
              .env_runners(num_envs_per_env_runner=8)
              .rl_module(model_config={"use_lstm": True,
                                       "lstm_cell_size": 32,
                                       "fcnet_hiddens": [32],
                                       "max_seq_len": 8})
              .training(train_batch_size=512, lr=3e-3,
                        entropy_coeff=0.01, num_sgd_iter=4,
                        rollout_fragment_length=256)
              .debugging(seed=0))
    algo = config.build()
    assert isinstance(algo.env_runner_group.spec, RecurrentRLModuleSpec)
    best = 0.0
    for _ in range(40):
        r = algo.step()
        best = max(best, r.get("episode_return_mean", 0.0))
        if best > 0.8:
            break
    algo.stop()
    assert best > 0.8, best


def test_lstm_impala_single_step_shapes():
    """Pure IMPALA consumes one recurrent V-trace batch without shape
    errors and reports trained steps from the mask."""
    from ray_tpu.rl.algorithms import IMPALAConfig

    config = (IMPALAConfig()
              .environment(env_fn=lambda: RecallEnv(length=4))
              .env_runners(num_envs_per_env_runner=4)
              .rl_module(model_config={"use_lstm": True,
                                       "lstm_cell_size": 8,
                                       "fcnet_hiddens": [8],
                                       "max_seq_len": 8})
              .training(rollout_fragment_length=64)
              .debugging(seed=0))
    algo = config.build()
    r = algo.step()
    algo.stop()
    assert r["num_env_steps_trained"] >= 64
    assert np.isfinite(r["total_loss"])


def test_custom_catalog_through_config():
    """catalog_class injection reaches the runner's spec inference."""
    class WideCatalog(Catalog):
        def build_module_spec(self):
            import dataclasses

            return dataclasses.replace(super().build_module_spec(),
                                       hidden_sizes=(17,))

    config = (PPOConfig()
              .environment("CartPole-v1")
              .rl_module(catalog_class=WideCatalog)
              .training(train_batch_size=128, minibatch_size=64,
                        num_epochs=1))
    algo = config.build()
    assert algo.env_runner_group.spec.hidden_sizes == (17,)
    algo.step()  # one full train iteration compiles and runs
    algo.stop()


def test_atari_scale_pixel_pipeline():
    """Atari-scale proof: 84x84 grayscale obs, frame-stack 4 (the
    standard Atari preprocessing, via FrameStackingConnector), the
    Nature-DQN conv stack auto-selected by the catalog, PPO training
    end to end.  Learning at this scale needs more steps than CI
    allows, so the assertions pin the pipeline: correct spec/shapes,
    finite losses, env steps flowing (the 10px BrightQuadrant test
    owns the conv LEARNING proof)."""
    from ray_tpu.rl import FrameStackingConnector

    config = (PPOConfig()
              .environment(env_fn=lambda: BrightQuadrantEnv(
                  size=84, length=8, patch=8))
              .env_runners(
                  num_envs_per_env_runner=4,
                  env_to_module_connector=lambda:
                      FrameStackingConnector(num_frames=4))
              .rl_module(model_config={})  # catalog inference (auto conv)
              .training(train_batch_size=128, minibatch_size=64,
                        num_epochs=2, lr=3e-4)
              .debugging(seed=0))
    algo = config.build()
    spec = algo.env_runner_group.spec
    assert isinstance(spec, ConvRLModuleSpec)
    assert spec.obs_shape == (84, 84, 4)  # stacked channel dim
    assert spec.conv_filters == ((32, 8, 4), (64, 4, 2), (64, 3, 1))
    result = {}
    for _ in range(2):
        result = algo.step()
    algo.stop()
    assert np.isfinite(result["total_loss"])
    assert result["num_env_steps_trained"] > 0
