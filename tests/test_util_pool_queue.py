"""ActorPool / Queue / runtime-context tests (reference:
python/ray/util/actor_pool.py, util/queue.py, runtime_context.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util.queue import Empty, Full


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _doubler_cls():
    # Local class: cloudpickle ships it by value (module-level test
    # classes pickle by reference and fail in workers).
    class Doubler:
        def work(self, x):
            return x * 2

    return Doubler


def test_actor_pool_map_ordered():
    actors = [ray_tpu.remote(_doubler_cls()).options(num_cpus=0.5).remote()
              for _ in range(3)]
    pool = ActorPool(actors)
    out = list(pool.map(lambda a, v: a.work.remote(v), range(10)))
    assert out == [x * 2 for x in range(10)]


def test_actor_pool_map_unordered_complete_set():
    actors = [ray_tpu.remote(_doubler_cls()).options(num_cpus=0.5).remote()
              for _ in range(2)]
    pool = ActorPool(actors)
    out = sorted(pool.map_unordered(
        lambda a, v: a.work.remote(v), range(8)))
    assert out == [x * 2 for x in range(8)]


def test_actor_pool_submit_get_next():
    actors = [ray_tpu.remote(_doubler_cls()).options(num_cpus=0.5).remote()]
    pool = ActorPool(actors)
    pool.submit(lambda a, v: a.work.remote(v), 5)
    pool.submit(lambda a, v: a.work.remote(v), 6)
    assert pool.get_next() == 10
    assert pool.get_next() == 12
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_fifo_and_cross_task():
    q = Queue()
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"

    # Handle pickles into tasks; items flow across processes.
    @ray_tpu.remote
    def producer(q):
        for i in range(3):
            q.put(i * 10)
        return True

    assert ray_tpu.get(producer.remote(q))
    got = [q.get(timeout=10) for _ in range(4)]  # 'b' + 0,10,20
    assert got == ["b", 0, 10, 20]
    q.shutdown()


def test_queue_blocking_get_unblocks_on_put():
    q = Queue()

    @ray_tpu.remote
    def slow_put(q):
        time.sleep(0.5)
        q.put("late")
        return True

    ref = slow_put.remote(q)
    t0 = time.time()
    assert q.get(timeout=10) == "late"  # blocks until the put lands
    assert time.time() - t0 >= 0.3
    ray_tpu.get(ref)
    q.shutdown()


def test_queue_timeout_and_bounds():
    q = Queue(maxsize=1)
    q.put("x")
    with pytest.raises(Full):
        q.put("y", timeout=0.2)
    assert q.get() == "x"
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


def test_runtime_context_in_task_and_actor():
    ctx = ray_tpu.get_runtime_context()
    assert ctx.worker_id and ctx.session_id
    assert ctx.get_task_id() is None  # driver

    @ray_tpu.remote(num_cpus=0.5, resources={"extra": 0})
    def who():
        c = ray_tpu.get_runtime_context()
        return (c.get_task_id(), c.get_assigned_resources())

    task_id, res = ray_tpu.get(who.remote())
    assert task_id and len(task_id) == 28
    assert res.get("CPU") == 0.5

    class A:
        def me(self):
            return ray_tpu.get_runtime_context().get_actor_id()

    a = ray_tpu.remote(A).remote()
    assert ray_tpu.get(a.me.remote())


# ---------------------------------------------------------------------------
# multiprocessing.Pool counterpart (reference ray.util.multiprocessing)
# ---------------------------------------------------------------------------

def test_mp_pool_map_and_starmap(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    # Defined in-test: cloudpickle ships nested functions by value, so
    # pool workers don't need the test module importable.
    def _sq(x):
        return x * x

    def _addmul(a, b):
        return a + 10 * b

    with Pool(processes=2) as p:
        assert p.map(_sq, range(10)) == [i * i for i in range(10)]
        assert p.starmap(_addmul, [(1, 2), (3, 4)]) == [21, 43]
        r = p.map_async(_sq, range(6), chunksize=2)
        r.wait(timeout=30)
        assert r.ready() and r.successful()
        assert r.get() == [0, 1, 4, 9, 16, 25]


def test_mp_pool_apply_and_imap(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    def _sq(x):
        return x * x

    def _addmul(a, b):
        return a + 10 * b

    p = Pool(processes=2)
    assert p.apply(_addmul, (2, 3)) == 32
    assert p.apply_async(_sq, (7,)).get(timeout=30) == 49
    assert list(p.imap(_sq, range(8), chunksize=3)) == \
        [i * i for i in range(8)]
    assert sorted(p.imap_unordered(_sq, range(8), chunksize=3)) == \
        sorted(i * i for i in range(8))
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])
    p.join()


def test_mp_pool_error_propagates(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    def boom(x):
        raise RuntimeError("pool task failed")

    with Pool(processes=2) as p:
        with pytest.raises(Exception, match="pool task failed"):
            p.map(boom, range(3))
        r = p.map_async(boom, range(3))
        r.wait(timeout=30)
        assert not r.successful()


# ---------------------------------------------------------------------------
# joblib backend (reference ray.util.joblib.register_ray)
# ---------------------------------------------------------------------------

def test_joblib_backend(ray_start_regular):
    from joblib import Parallel, delayed, parallel_backend

    from ray_tpu.util.joblib import register_ray_tpu

    def _sq(x):
        return x * x

    register_ray_tpu()
    with parallel_backend("ray_tpu", n_jobs=2):
        out = Parallel()(delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]


def test_mp_pool_window_and_timeout_semantics(ray_start_regular):
    """processes bounds in-flight tasks; get(timeout) raises
    multiprocessing.TimeoutError; join waits for outstanding work."""
    import time as _time
    from multiprocessing import TimeoutError as MpTimeoutError

    from ray_tpu.util.multiprocessing import Pool

    def slowsq(x):
        _time.sleep(0.2)
        return x * x

    p = Pool(processes=2)
    r = p.map_async(slowsq, range(8), chunksize=1)
    # Window: at most `processes` chunks submitted before results land.
    assert len(r._chunks.refs) <= 2
    with pytest.raises(MpTimeoutError):
        r.get(timeout=0.05)
    p.close()
    p.join()  # blocks until everything ran
    assert r.ready()
    assert r.get() == [i * i for i in range(8)]


def test_joblib_backend_class_importable():
    from ray_tpu.util.joblib import RayTpuBackend

    assert RayTpuBackend is not None and isinstance(RayTpuBackend, type)
