"""Serve data plane under load: engine admission control (bounded queue,
deadline shedding, abort reclamation, per-step prefill budget),
load-feedback P2C routing with staleness fallback, the multiplex model
cache's concurrency guarantees, and the SERVE_BENCH.json artifact
thresholds (scripts/bench_serve.py).

These are unit tests — no cluster; the engine runs the tiny CPU config
and the router is exercised directly against an injected replica set.
"""

import json
import os
import threading
import time

import pytest

from ray_tpu.models import transformer as tfm
from ray_tpu.serve.llm_engine import LLMEngine, QueueFull


def _engine(**over):
    kw = dict(page_size=4, num_pages=64, max_batch=4,
              enable_prefix_caching=False, queue_timeout_s=0)
    kw.update(over)
    return LLMEngine(tfm.TransformerConfig.tiny(), **kw)


# ---------------------------------------------------------------------------
# Engine admission control
# ---------------------------------------------------------------------------


def test_admission_queue_full_backpressure():
    """Past max_queue, add_request raises QueueFull at the door — the
    one point where the caller can still retry another replica —
    instead of growing the waiting queue without bound."""
    eng = _engine(max_queue=2)
    eng.add_request([1, 2, 3], 4)
    eng.add_request([4, 5, 6], 4)
    with pytest.raises(QueueFull, match="cap 2"):
        eng.add_request([7, 8, 9], 4)
    assert eng.num_shed == 1
    assert len(eng.waiting) == 2  # the reject didn't enqueue


def test_admission_deadline_shed_on_burst():
    """Requests whose queueing deadline passes before they reach a slot
    are shed at the next step with reason 'deadline' (the waiter gets
    RequestShed through serve/llm.py, not an indefinite hang)."""
    eng = _engine(max_batch=2)
    ids = [eng.add_request([10 + i, 11 + i], 4, deadline_s=0.02)
           for i in range(3)]
    time.sleep(0.08)
    done = eng.step()
    assert done == {}
    assert not eng.waiting
    assert eng.num_shed == 3
    assert {rid: eng.shed[rid] for rid in ids} == \
        {rid: "deadline" for rid in ids}


def test_abort_frees_slot_and_kv_pages():
    """Mid-generation abort (the disconnect path) returns the slot and
    every KV page to the pool, and the engine keeps serving afterwards
    (dirty-slot cleanup doesn't poison later requests)."""
    eng = _engine(max_batch=2, num_pages=32)
    free0 = eng.allocator.num_free
    rid = eng.add_request([1, 2, 3, 4], 16)
    for _ in range(5):
        eng.step()
        if eng.num_active:
            break
    assert eng.num_active == 1
    assert eng.allocator.num_free < free0
    assert eng.abort(rid) is True
    assert eng.num_active == 0
    assert eng.allocator.num_free == free0
    assert eng.shed == {rid: "aborted"}
    assert eng.num_aborted == 1
    assert eng.abort(rid) is False  # already gone

    # The engine is still healthy: a follow-up request completes.
    eng.shed.clear()
    rid2 = eng.add_request([5, 6, 7], 4)
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid2 in done:
            break
    assert len(done[rid2]) == 4


def test_prefill_budget_interleaves_admission():
    """With a per-step prefill token budget the engine admits a prompt
    burst over several steps (decode slots keep stepping in between);
    with the budget disabled the same burst seats in one wave."""
    prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5, i + 6, i + 7, i + 8]
               for i in (0, 10, 20)]

    def steps_to_seat(eng):
        for p in prompts:
            eng.add_request(list(p), 32)
        for n in range(1, 10):
            eng.step()
            if eng.num_active == 3:
                return n
        return 10

    budgeted = steps_to_seat(_engine(prefill_budget=8))
    unbudgeted = steps_to_seat(_engine(prefill_budget=0))
    # 3 x 8-token prompts at 8 tokens/step: one admission per step.
    assert budgeted >= 3
    assert unbudgeted < budgeted


# ---------------------------------------------------------------------------
# Load-feedback routing (router.py): P2C over piggybacked reports
# ---------------------------------------------------------------------------

_HEX_A = "a" * 32
_HEX_B = "b" * 32


def _mk_router():
    """A Router wired to an injected replica set — no controller, no
    poll thread, no cluster; exactly the state assign_replica reads."""
    from ray_tpu.serve import router as router_mod

    r = router_mod.Router.__new__(router_mod.Router)
    r.app_name = "app"
    r.deployment = "dep"
    r._set = router_mod._ReplicaSet()
    s = r._set
    with s.cv:
        s.entries = [{"actor_hex": _HEX_A, "max_ongoing": 8},
                     {"actor_hex": _HEX_B, "max_ongoing": 8}]
        for e in s.entries:
            s.handles[e["actor_hex"]] = object()
            s.inflight.setdefault(e["actor_hex"], 0)
    return r


def test_router_fresh_feedback_steers_to_shallow_queue():
    r = _mk_router()
    r._set.update_reports({
        _HEX_A: {"queue_depth": 0, "free_kv_pages": 10},
        _HEX_B: {"queue_depth": 50, "free_kv_pages": 10},
    })
    for _ in range(10):
        hex_id, _ = r.assign_replica(timeout_s=1)
        assert hex_id == _HEX_A  # P2C always sees both; A's score wins
        r.release(hex_id)


def test_router_kv_exhaustion_penalty():
    """An exhausted KV pool outweighs a small queue: every admission
    there would stall on pages."""
    r = _mk_router()
    r._set.update_reports({
        _HEX_A: {"queue_depth": 0, "free_kv_pages": 0},
        _HEX_B: {"queue_depth": 2, "free_kv_pages": 64},
    })
    now = time.monotonic()
    a, b = r._set.entries
    assert r._score(a, now, 5.0) == (4.0, True)
    assert r._score(b, now, 5.0) == (2.0, True)
    hex_id, _ = r.assign_replica(timeout_s=1)
    assert hex_id == _HEX_B


def test_router_stale_feedback_falls_back_to_local_signal():
    """A report older than RAY_TPU_SERVE_FEEDBACK_STALE_S is ignored
    (fossil data from a wedged controller must not steer traffic); the
    blind local in-flight count decides instead."""
    r = _mk_router()
    r._set.update_reports({_HEX_B: {"queue_depth": 100}})
    r._set.reports[_HEX_B]["received_at"] -= 60.0  # age past staleness
    r._set.inflight[_HEX_A] = 5
    now = time.monotonic()
    b = r._set.entries[1]
    score, fresh = r._score(b, now, 5.0)
    assert (score, fresh) == (0.0, False)  # depth-100 report ignored
    hex_id, _ = r.assign_replica(timeout_s=1)
    assert hex_id == _HEX_B


def test_router_model_affinity_prefers_loaded_replica():
    """A fresh report listing the requested multiplex model restricts
    the P2C pool to replicas that skip the cold load; once the report
    goes stale the affinity bias disappears."""
    r = _mk_router()
    r._set.update_reports({
        _HEX_A: {"queue_depth": 0, "models": []},
        _HEX_B: {"queue_depth": 3, "models": ["m1"]},
    })
    r._set.inflight[_HEX_B] = 3
    hex_id, _ = r.assign_replica(timeout_s=1, model_id="m1")
    assert hex_id == _HEX_B  # affinity beats the load gap
    r.release(hex_id)

    now = time.monotonic()
    b = r._set.entries[1]
    assert r._has_model(b, "m1", now, 5.0)
    r._set.reports[_HEX_B]["received_at"] -= 60.0
    assert not r._has_model(b, "m1", now, 5.0)


def test_router_staleness_knob(monkeypatch):
    from ray_tpu.serve.router import _stale_s

    monkeypatch.setenv("RAY_TPU_SERVE_FEEDBACK_STALE_S", "2.5")
    assert _stale_s() == 2.5
    monkeypatch.setenv("RAY_TPU_SERVE_FEEDBACK_STALE_S", "bogus")
    assert _stale_s() == 5.0


# ---------------------------------------------------------------------------
# Multiplex model cache: single-flight loads, pinned models never evict
# ---------------------------------------------------------------------------


def test_model_cache_single_flight_concurrent_loads():
    from ray_tpu.serve.multiplex import _ModelCache

    loads = []

    def loader(mid):
        loads.append(mid)
        time.sleep(0.2)  # wide window for racers to pile in
        return {"id": mid}

    cache = _ModelCache(loader, capacity=2)
    out = []
    lock = threading.Lock()

    def hit():
        m = cache.get(None, "m1")
        with lock:
            out.append(m)

    threads = [threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(out) == 8
    assert cache.load_count == 1 and loads == ["m1"]
    assert all(m is out[0] for m in out)  # one object, shared


def test_model_cache_never_evicts_pinned_model():
    from ray_tpu.serve.multiplex import _ModelCache

    class Model:
        def __init__(self):
            self.unloaded = False

        def unload(self):
            self.unloaded = True

    cache = _ModelCache(lambda mid: Model(), capacity=1)
    m1 = cache.get(None, "m1")  # pinned by the get
    m2 = cache.get(None, "m2")  # over capacity, but m1 is in use
    assert set(cache.loaded_ids()) == {"m1", "m2"}  # overflow, no evict
    assert not m1.unloaded
    cache.unpin("m1")  # request finished -> deferred eviction runs
    assert cache.loaded_ids() == ["m2"]
    assert m1.unloaded and not m2.unloaded
    assert cache.pinned_ids() == ["m2"]


def test_model_cache_failed_load_retries_fresh():
    from ray_tpu.serve.multiplex import _ModelCache

    calls = {"n": 0}

    def loader(mid):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("flaky checkpoint")
        return mid.upper()

    cache = _ModelCache(loader, capacity=2)
    with pytest.raises(ValueError, match="flaky checkpoint"):
        cache.get(None, "m")
    assert cache.get(None, "m") == "M"  # no poisoned loading marker


# ---------------------------------------------------------------------------
# Serve observability: metrics + flight-recorder "serve" lane
# ---------------------------------------------------------------------------


def test_serve_metrics_and_flight_recorder_lane():
    """Admission decisions are observable: the serve counter/gauge
    series show up in the local metric snapshots (so /metrics exports
    them) and the flight recorder's "serve" lane records the
    queue_full / shed / abort decisions."""
    from ray_tpu.util import flight_recorder
    from ray_tpu.util.metrics import local_snapshots

    flight_recorder.configure(enable=True)
    flight_recorder.clear()
    eng = _engine(max_queue=1, max_batch=2)
    eng.add_request([1, 2], 4)
    with pytest.raises(QueueFull):
        eng.add_request([3, 4], 4)
    names = {s["name"] for s in local_snapshots()}
    assert {"ray_tpu_serve_requests_total", "ray_tpu_serve_shed_total",
            "ray_tpu_serve_queue_depth"} <= names
    events = [(e["category"], e["event"])
              for e in flight_recorder.dump(last=50)]
    assert ("serve", "queue_full") in events


def test_serve_bench_artifact_thresholds():
    bench = os.path.join(os.path.dirname(__file__), os.pardir,
                         "SERVE_BENCH.json")
    if not os.path.exists(bench):
        pytest.skip("SERVE_BENCH.json not generated")
    with open(bench) as f:
        doc = json.load(f)
    assert doc["concurrent_clients"] >= 1024
    sus = doc["sustained_load"]
    assert sus["tokens_per_sec"] > 0
    assert 0 < sus["ttft_p50_s"] <= sus["ttft_p99_s"]
    assert 0 < sus["tpot_p50_ms"] <= sus["tpot_p99_ms"]
    burst = doc["burst_shed"]
    # Backpressure fired: the 4x-cap burst was shed, not queued forever.
    assert burst["queue_full_rejects"] > 0
    assert burst["shed_rate"] > 0
    assert burst["completed"] + burst["deadline_sheds"] \
        + burst["queue_full_rejects"] == burst["burst_clients"]
    pi = doc["prefill_interference"]
    assert pi["decode_tpot_p99_ms_alone"] > 0
    assert pi["prefill_requests_injected"] > 0
    if doc.get("on_tpu"):
        # TPU acceptance bars (CPU runs are dispatch-bound, so the
        # roofline fraction and the TPOT isolation bar only bind there).
        assert doc["roofline_fraction"] > 0.378
        assert pi["tpot_ratio"] <= 1.2
