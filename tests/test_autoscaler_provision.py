"""Real provisioning path: command runners + node updater + cluster
launcher SDK (reference: autoscaler/_private/command_runner.py,
updater.py, commands.py `ray up/down`, local node provider).

Uses provider type "local": the identical updater flow as SSH, with
commands running through a local shell — head and worker node daemons
are real separate processes started by the runner."""

import json
import socket
import subprocess
import time

import pytest
import yaml

from ray_tpu.autoscaler import sdk
from ray_tpu.autoscaler.command_runner import (
    LocalCommandRunner,
    SSHCommandRunner,
    wait_ready,
)
from ray_tpu.autoscaler.updater import (
    STATUS_FAILED,
    STATUS_UP_TO_DATE,
    NodeUpdater,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_local_runner_and_wait_ready(tmp_path):
    r = LocalCommandRunner()
    assert "hello" in r.run("echo hello")
    wait_ready(r, timeout=10)
    src = tmp_path / "a.txt"
    src.write_text("data")
    r.run_rsync_up(str(src), str(tmp_path / "b" / "a.txt"))
    assert (tmp_path / "b" / "a.txt").read_text() == "data"
    with pytest.raises(subprocess.CalledProcessError):
        r.run("exit 3")


def test_ssh_runner_command_shape():
    """No sshd in the test env: verify the constructed invocation only."""
    r = SSHCommandRunner("10.0.0.9", user="tpu", ssh_key="/k.pem",
                         port=2222)
    line = r.remote_shell_command_str()
    assert line == "ssh -i /k.pem -p 2222 tpu@10.0.0.9"
    assert "-o" in r._opts and "ControlMaster=auto" in r._opts


def test_updater_failure_surfaces(tmp_path):
    upd = NodeUpdater(
        "n-bad", LocalCommandRunner(), head_address="127.0.0.1:1",
        setup_commands=["exit 7"], ready_timeout=10)
    assert upd.run() is False
    assert upd.status == STATUS_FAILED
    assert "rc=7" in upd.error


def test_up_provisions_and_down_tears_down(tmp_path):
    port = _free_port()
    config_path = tmp_path / "cluster.yaml"
    marker = tmp_path / "setup-ran.txt"
    config_path.write_text(yaml.safe_dump({
        "cluster_name": "prov-test",
        "max_workers": 2,
        "provider": {"type": "local", "head_ip": "127.0.0.1",
                     "head_port": port, "nodes_per_host": 0,
                     "worker_ips": ["127.0.0.1"]},
        "setup_commands": [f"echo ok >> {marker}"],
        "head_node": {"CPU": 2},
        "worker_nodes": {"CPU": 2},
    }))
    config = sdk.load_config(str(config_path))
    report = sdk.create_or_update_cluster(config)
    try:
        assert not report["failed"], report["failed"]
        assert len(report["workers"]) == 2
        assert all(w["status"] == STATUS_UP_TO_DATE
                   for w in report["workers"])
        # setup commands really ran (once per worker)
        assert marker.read_text().count("ok") == 2

        # the cluster is real: a driver can join and see 3 nodes + run work
        out = subprocess.run(
            ["python", "-c", f"""
import ray_tpu, json
from ray_tpu.state import list_nodes
ray_tpu.init(address="127.0.0.1:{port}")
nodes = [n for n in list_nodes() if n["alive"]]
total = ray_tpu.cluster_resources()
print(json.dumps([len(nodes), total.get("CPU")]))
"""], capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr[-2000:]
        n_nodes, cpus = json.loads(out.stdout.strip().splitlines()[-1])
        assert n_nodes == 3  # head + 2 provisioned workers
        assert cpus == 6.0
    finally:
        sdk.teardown_cluster(config)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and sdk._head_alive(config):
        time.sleep(0.5)
    assert not sdk._head_alive(config)
