"""Wire-conformance corpus (VERDICT r5 item 8: the cross-language
contract artifact standing in for the reference's proto IDL tier).

Three layers:
  1. drift: the committed WIRE_CONFORMANCE.json regenerates
     byte-identically from the live schema (a schema change without a
     corpus regeneration fails here);
  2. replay: every golden frame, decoded exactly as the JSON door
     decodes (rpc._from_jsonable on the parsed JSON), validates — or
     fails validation — as recorded;
  3. C++ client: the frames the in-tree C++ client emits (client.h
     hand-built JSON) decode+validate against the same schema.
"""

import json
import os
import pathlib
import re

import pytest

from ray_tpu.core.rpc import _from_jsonable
from ray_tpu.core.wire_schema import SchemaError, validate

_REPO = pathlib.Path(__file__).resolve().parent.parent
_DOC = _REPO / "WIRE_CONFORMANCE.json"


def _load():
    with open(_DOC) as f:
        return json.load(f)


def test_corpus_matches_live_schema():
    import sys

    sys.path.insert(0, str(_REPO / "scripts"))
    from gen_wire_conformance import build_corpus

    committed = _load()
    assert committed == json.loads(json.dumps(build_corpus())), (
        "wire schema changed without regenerating the corpus: run "
        "python scripts/gen_wire_conformance.py")


def test_golden_frames_replay_through_ingress_validation():
    doc = _load()
    assert len(doc["golden"]) > 200
    n_valid = n_invalid = 0
    for case in doc["golden"]:
        # Decode exactly as the JSON door does before validate().
        frame = _from_jsonable(case["frame"])
        if case["valid"]:
            validate(frame)  # must not raise
            n_valid += 1
        else:
            with pytest.raises(SchemaError):
                validate(frame)
            n_invalid += 1
    assert n_valid >= 90 and n_invalid >= 150


def test_every_schema_op_has_golden_coverage():
    doc = _load()
    ops_in_schema = set(doc["schema"]["ops"])
    covered = {g["op"] for g in doc["golden"] if g["valid"]}
    assert ops_in_schema <= covered


def _cpp_emitted_frames():
    """Frames the C++ client hand-builds (client.h + worker.h):
    extracted from the literal {\\"op\\":...} templates with the
    placeholders filled the way the code fills them."""
    return [
        {"op": "kv_put", "key": "k", "value": "v", "overwrite": True},
        {"op": "kv_get", "key": "k"},
        {"op": "kv_del", "key": "k"},
        {"op": "kv_exists", "key": "k"},
        {"op": "kv_keys", "prefix": "p"},
        {"op": "submit_named_task", "name": "Add", "args": [2, 3],
         "num_cpus": 1.0},
        {"op": "get_object_json", "obj": "ab" * 14},
        {"op": "object_shm_info", "obj": "ab" * 14},
        {"op": "register_cpp_functions", "functions": ["Add"],
         "actor_classes": ["Counter"]},
        {"op": "cpp_task_done", "return": "ab" * 14, "result": 5.0},
        {"op": "cpp_task_done", "return": "ab" * 14, "error": "boom"},
        {"op": "create_cpp_actor", "actor_class": "Counter",
         "args": [10]},
        {"op": "submit_cpp_actor_task", "instance": "i1",
         "method": "Inc", "args": [5]},
        {"op": "list_cpp_functions"},
        {"op": "cluster_resources"},
        {"op": "available_resources"},
        {"op": "ping"},
    ]


def test_cpp_client_frames_conform():
    """Every frame shape the C++ client/worker emits passes the same
    ingress validation the corpus pins — the 'third-language client
    validated against the golden contract' leg, using the in-tree C++
    frontend as that client."""
    for frame in _cpp_emitted_frames():
        validate(frame)


def test_cpp_sources_emit_only_schema_ops():
    """Static sweep: every \"op\":\"...\" literal in the C++ sources
    names an op the schema declares (a renamed/added C++ op without a
    schema row fails here before any runtime test could)."""
    ops = set(_load()["schema"]["ops"])
    pat = re.compile(r'\\"op\\":\\"([a-z_]+)\\"')
    found = set()
    for root, _, files in os.walk(_REPO / "cpp"):
        for fn in files:
            if fn.endswith((".h", ".cc", ".cpp")):
                text = open(os.path.join(root, fn)).read()
                found |= set(pat.findall(text))
    assert found, "no op literals found in cpp/ — pattern drift?"
    unknown = found - ops
    assert not unknown, f"C++ emits ops outside the contract: {unknown}"
