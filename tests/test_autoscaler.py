"""Autoscaler tests (reference: python/ray/tests/test_autoscaler.py with
MockProvider + test_autoscaler_fake_multinode.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    FakeMultiNodeProvider,
    NodeTypeConfig,
    fit_demands,
)
from ray_tpu.cluster_utils import Cluster


# ---------------------------------------------------------------------------
# pure bin-packing unit tests

def test_fit_demands_uses_spare_capacity_first():
    to_add, infeasible = fit_demands(
        demands=[{"CPU": 1}, {"CPU": 1}],
        spare_capacity=[{"CPU": 2}],
        node_types={"cpu4": {"CPU": 4}},
        max_per_type={"cpu4": 5},
        current_counts={},
    )
    assert to_add == {} and infeasible == []


def test_fit_demands_launches_cheapest_feasible_type():
    to_add, infeasible = fit_demands(
        demands=[{"CPU": 2}],
        spare_capacity=[],
        node_types={"big": {"CPU": 16, "TPU": 4}, "small": {"CPU": 4}},
        max_per_type={"big": 5, "small": 5},
        current_counts={},
    )
    assert to_add == {"small": 1} and infeasible == []


def test_fit_demands_packs_multiple_onto_one_new_node():
    to_add, _ = fit_demands(
        demands=[{"CPU": 1}] * 4,
        spare_capacity=[],
        node_types={"cpu4": {"CPU": 4}},
        max_per_type={"cpu4": 5},
        current_counts={},
    )
    assert to_add == {"cpu4": 1}


def test_fit_demands_respects_max_per_type():
    to_add, infeasible = fit_demands(
        demands=[{"CPU": 4}] * 3,
        spare_capacity=[],
        node_types={"cpu4": {"CPU": 4}},
        max_per_type={"cpu4": 2},
        current_counts={},
    )
    assert to_add == {"cpu4": 2}
    assert len(infeasible) == 1


def test_fit_demands_tpu_demand_picks_tpu_type():
    to_add, _ = fit_demands(
        demands=[{"TPU": 4}],
        spare_capacity=[{"CPU": 64}],
        node_types={"cpu": {"CPU": 64}, "v4-host": {"TPU": 4, "CPU": 120}},
        max_per_type={"cpu": 5, "v4-host": 2},
        current_counts={},
    )
    assert to_add == {"v4-host": 1}


# ---------------------------------------------------------------------------
# end-to-end with the fake provider on a live cluster

@pytest.fixture
def scaling_cluster():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    import ray_tpu.core.runtime as rt_mod

    yield cluster
    cluster.shutdown()


def _mk_autoscaler(cluster, **cfg_overrides):
    provider = FakeMultiNodeProvider(cluster)
    cfg = AutoscalerConfig(
        node_types={"cpu2": NodeTypeConfig({"CPU": 2}, max_workers=3)},
        idle_timeout_s=cfg_overrides.pop("idle_timeout_s", 60.0),
        **cfg_overrides,
    )
    return Autoscaler(cluster.runtime.kv().call, provider, cfg)


def test_scale_up_on_pending_demand(scaling_cluster):
    cluster = scaling_cluster
    autoscaler = _mk_autoscaler(cluster)

    @ray_tpu.remote(num_cpus=2)
    def heavy():
        return "ok"

    # head has 1 CPU; this task cannot run until a cpu2 node appears
    ref = heavy.remote()
    time.sleep(0.3)  # let the task reach the pending queue
    launched = autoscaler.step()
    assert launched == {"cpu2": 1}
    assert ray_tpu.get([ref], timeout=30)[0] == "ok"


def test_scale_up_capped_by_max_workers(scaling_cluster):
    cluster = scaling_cluster
    autoscaler = _mk_autoscaler(cluster)

    @ray_tpu.remote(num_cpus=2)
    def heavy(i):
        time.sleep(0.5)
        return i

    refs = [heavy.remote(i) for i in range(8)]
    time.sleep(0.3)
    for _ in range(5):
        autoscaler.step()
    assert len(autoscaler.provider.non_terminated_nodes()) <= 3
    assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(8))


def test_scale_down_idle_nodes(scaling_cluster):
    cluster = scaling_cluster
    autoscaler = _mk_autoscaler(cluster, idle_timeout_s=0.2)
    nid = autoscaler.provider.create_node("cpu2", {"CPU": 2})
    assert len(autoscaler.provider.non_terminated_nodes()) == 1
    autoscaler.step()  # records idle_since
    time.sleep(0.3)
    autoscaler.step()  # past timeout: DRAIN first (not terminate)
    # Drain-before-terminate: the provider instance survives until the
    # head reports the drain complete (node gone), then releases.
    deadline = time.time() + 10
    while time.time() < deadline:
        autoscaler.step()
        if autoscaler.provider.non_terminated_nodes() == []:
            break
        time.sleep(0.2)
    assert autoscaler.provider.non_terminated_nodes() == []
    alive = [n for n in cluster.list_nodes() if n["alive"]]
    assert all(n["node_id"] != nid for n in alive)


def test_min_workers_maintained(scaling_cluster):
    cluster = scaling_cluster
    provider = FakeMultiNodeProvider(cluster)
    cfg = AutoscalerConfig(
        node_types={"cpu2": NodeTypeConfig({"CPU": 2}, min_workers=2,
                                           max_workers=4)},
        idle_timeout_s=0.01,
    )
    autoscaler = Autoscaler(cluster.runtime.kv().call, provider, cfg)
    autoscaler.step()
    assert len(provider.non_terminated_nodes()) == 2
    # idle min_workers nodes are NOT scaled down
    time.sleep(0.1)
    autoscaler.step()
    time.sleep(0.1)
    autoscaler.step()
    assert len(provider.non_terminated_nodes()) == 2


def test_infeasible_demand_reported(scaling_cluster):
    cluster = scaling_cluster
    autoscaler = _mk_autoscaler(cluster)

    @ray_tpu.remote(num_cpus=64)
    def impossible():
        return 1

    ref = impossible.remote()  # noqa: F841 held pending forever
    time.sleep(0.3)
    autoscaler.step()
    assert autoscaler.last_infeasible == [{"CPU": 64.0}]
