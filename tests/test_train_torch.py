"""TorchTrainer tests: gloo process group over the worker group, DDP
model wrap, distributed sampler sharding (SURVEY.md §2.3 L2 Torch
backend counterpart)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import ScalingConfig, TorchTrainer
from ray_tpu.train import session as train_session


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_torch_trainer_ddp_two_workers():
    """2 workers: DDP gradient averaging makes both ranks' models
    identical after training on DIFFERENT data shards; losses converge
    on a linear-regression toy."""

    def loop(config):
        import torch
        import torch.distributed as dist
        import torch.nn as nn

        from ray_tpu.train.torch_backend import prepare_model

        ctx = train_session.get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        assert dist.is_initialized() and dist.get_world_size() == world

        torch.manual_seed(0)  # same init on both ranks
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)

        g = torch.Generator().manual_seed(100 + rank)  # distinct shards
        X = torch.randn(64, 4, generator=g)
        w_true = torch.tensor([[1.0, -2.0, 3.0, 0.5]]).T
        y = X @ w_true

        loss_val = None
        for _ in range(60):
            opt.zero_grad()
            loss = ((model(X) - y) ** 2).mean()
            loss.backward()  # DDP averages grads across ranks here
            opt.step()
            loss_val = float(loss)

        w = model.module.weight.detach().numpy().copy() \
            if hasattr(model, "module") else \
            model.weight.detach().numpy().copy()
        # History records rank 0's reports (reference semantics), so
        # gather every rank's weights before reporting.
        gathered = [None] * world
        dist.all_gather_object(gathered, w.tolist())
        train_session.report({"loss": loss_val, "rank": rank,
                              "all_weights": gathered})

    result = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
    ).fit()
    assert result.metrics["loss"] < 0.05, result.metrics
    w0, w1 = result.metrics["all_weights"]
    # DDP keeps replicas in sync: both ranks end with identical weights.
    np.testing.assert_allclose(w0, w1, rtol=1e-6)
    # And near the true weights.
    np.testing.assert_allclose(
        np.asarray(w0).ravel(), [1.0, -2.0, 3.0, 0.5], atol=0.2)


def test_prepare_data_loader_shards_per_rank():
    def loop(config):
        import torch
        from torch.utils.data import DataLoader, TensorDataset

        from ray_tpu.train.torch_backend import prepare_data_loader

        import torch.distributed as dist

        ctx = train_session.get_context()
        ds = TensorDataset(torch.arange(20).float())
        loader = prepare_data_loader(DataLoader(ds, batch_size=5))
        seen = sorted(int(x) for batch in loader for x in batch[0])
        gathered = [None] * ctx.get_world_size()
        dist.all_gather_object(gathered, seen)
        train_session.report({"per_rank": gathered})

    result = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
    ).fit()
    r0, r1 = result.metrics["per_rank"]
    # Each rank sees half the dataset; together they cover everything.
    assert len(r0) == 10 and len(r1) == 10
    assert sorted(r0 + r1) == list(range(20))


def test_torch_trainer_single_worker_no_group():
    def loop(config):
        import torch.distributed as dist

        from ray_tpu.train.torch_backend import prepare_model
        import torch.nn as nn

        assert not dist.is_initialized()
        model = prepare_model(nn.Linear(2, 1))
        assert not hasattr(model, "module")  # no DDP wrap solo
        train_session.report({"ok": 1})

    result = TorchTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.metrics["ok"] == 1
