"""Tests for the non-PPO algorithm families: DQN, SAC, IMPALA/APPO, BC.

Mirrors the reference's rllib test strategy (SURVEY.md §4): unit tests on
the pieces (replay buffers, V-trace math, losses) plus small learning
tests with modest reward thresholds (the tuned_examples envelopes scaled
down to CI size).
"""

import numpy as np
import pytest

from ray_tpu.rl import module as rl_module
from ray_tpu.rl.episode import SingleAgentEpisode
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


def _episode(rewards, terminated=True, obs_dim=3, n_actions=2):
    ep = SingleAgentEpisode()
    ep.add_reset(np.zeros(obs_dim))
    for t, r in enumerate(rewards):
        ep.add_step(np.full(obs_dim, t + 1.0), t % n_actions, r,
                    terminated=terminated and t == len(rewards) - 1,
                    logp=-0.7)
    return ep


# ---------------------------------------------------------------------------
# Replay buffers
# ---------------------------------------------------------------------------

def test_replay_buffer_nstep_rows():
    buf = ReplayBuffer(100, n_step=2, gamma=0.5)
    buf.add_episodes([_episode([1.0, 2.0, 4.0])])
    assert len(buf) == 3
    s = buf._storage
    # t=0: r = 1 + .5*2 = 2, next_obs = obs[2], discount .25, not done
    assert s["rewards"][0] == pytest.approx(2.0)
    np.testing.assert_allclose(s["next_obs"][0], np.full(3, 2.0))
    assert s["discounts"][0] == pytest.approx(0.25)
    assert s["dones"][0] == 0.0
    # t=1: window reaches terminal: r = 2 + .5*4 = 4, done
    assert s["rewards"][1] == pytest.approx(4.0)
    assert s["dones"][1] == 1.0
    # t=2: 1-step tail: r = 4, discount .5, done
    assert s["rewards"][2] == pytest.approx(4.0)
    assert s["discounts"][2] == pytest.approx(0.5)
    batch = buf.sample(16)
    assert batch["obs"].shape == (16, 3)
    assert batch["weights"].shape == (16,)


def test_replay_buffer_truncated_episode_bootstraps():
    buf = ReplayBuffer(100, n_step=1, gamma=0.9)
    ep = _episode([1.0, 1.0], terminated=False)
    ep.truncated = True
    buf.add_episodes([ep])
    # Truncation is not a terminal: done=0 so the TD target bootstraps.
    assert buf._storage["dones"][:2].sum() == 0.0


def test_prioritized_replay_prefers_high_td():
    buf = PrioritizedReplayBuffer(100, alpha=1.0, beta=1.0, n_step=1,
                                  gamma=0.99, seed=0)
    buf.add_episodes([_episode([1.0] * 10)])
    # Index 0 gets ~90% of the probability mass.
    buf.update_priorities(np.arange(10), np.array([10.0] + [0.1] * 9))
    batch = buf.sample(256)
    counts = np.bincount(batch["indices"], minlength=10)
    assert counts[0] > 180
    # IS weights: rare rows get the max weight (1.0 after normalization).
    assert batch["weights"].max() == pytest.approx(1.0)
    assert batch["weights"][batch["indices"] == 0].max() < 0.1


# ---------------------------------------------------------------------------
# Module specs
# ---------------------------------------------------------------------------

def test_qnetwork_spec_act_is_greedy():
    import jax

    spec = rl_module.QNetworkSpec(obs_dim=4, action_dim=3,
                                  hidden_sizes=(8,), dueling=True)
    params = spec.init(jax.random.key(0))
    obs = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    q = spec.q_values(params["online"], obs)
    a, logp, v = spec.act(params, obs, jax.random.key(1), True)
    np.testing.assert_array_equal(np.asarray(a), np.argmax(q, axis=-1))
    np.testing.assert_allclose(np.asarray(v), np.max(q, axis=-1),
                               rtol=1e-5)
    # init: online == target
    np.testing.assert_allclose(
        np.asarray(params["online"]["adv"]["layers"][0]["w"]),
        np.asarray(params["target"]["adv"]["layers"][0]["w"]))


def test_sac_spec_actions_in_bounds_and_logp_finite():
    import jax

    spec = rl_module.SACModuleSpec(
        obs_dim=3, action_dim=2, action_low=(-2.0, -1.0),
        action_high=(2.0, 3.0), hidden_sizes=(8,))
    params = spec.init(jax.random.key(0))
    obs = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
    a, logp = spec.sample_action(params["actor"], obs, jax.random.key(1))
    a = np.asarray(a)
    assert a.shape == (64, 2)
    assert (a[:, 0] >= -2.0).all() and (a[:, 0] <= 2.0).all()
    assert (a[:, 1] >= -1.0).all() and (a[:, 1] <= 3.0).all()
    assert np.isfinite(np.asarray(logp)).all()


# ---------------------------------------------------------------------------
# V-trace
# ---------------------------------------------------------------------------

def test_vtrace_on_policy_reduces_to_td_lambda1():
    """With target == behavior policy (rho = c = 1), vs equals the
    discounted Monte-Carlo return bootstrapped off the value fn — i.e.
    TD(λ=1) — for a terminated episode."""
    import jax

    from ray_tpu.rl.algorithms.impala import compute_vtrace

    spec = rl_module.RLModuleSpec(obs_dim=3, action_dim=2)
    params = rl_module.init_params(spec, jax.random.key(0))
    ep = _episode([1.0, 2.0, 3.0])
    # Make behavior logp exactly the current policy's logp → rho = 1.
    import jax.numpy as jnp
    obs = np.asarray(ep.finalize().obs)[:3].reshape(3, -1)
    di, _ = rl_module.forward(params, jnp.asarray(obs))
    ep.logp = np.asarray(spec.dist(di).logp(jnp.asarray(ep.actions)),
                         dtype=np.float32)
    rows = compute_vtrace([ep], params, spec, gamma=0.9)
    _, v_all = rl_module.forward(
        params, jnp.asarray(np.asarray(ep.obs).reshape(4, -1)))
    v = np.asarray(v_all)
    # Hand-rolled backward recursion with rho = c = 1:
    # vs[t] - v[t] = delta[t] + gamma * (vs[t+1] - v[t+1]).
    rewards = [1.0, 2.0, 3.0]
    v_next = [v[1], v[2], 0.0]  # terminal: v(s_T) = 0
    expect = np.zeros(3)
    acc = 0.0
    for t in range(2, -1, -1):
        delta = rewards[t] + 0.9 * v_next[t] - v[t]
        acc = delta + 0.9 * acc
        expect[t] = v[t] + acc
    np.testing.assert_allclose(rows[0]["value_targets"], expect, rtol=1e-4)


# ---------------------------------------------------------------------------
# Learning tests (small envelopes)
# ---------------------------------------------------------------------------

def test_dqn_cartpole_learns():
    from ray_tpu.rl.algorithms import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=128)
              .training(train_batch_size=64, lr=1e-3,
                        hidden_sizes=(64, 64),
                        target_network_update_freq=100,
                        num_steps_sampled_before_learning_starts=1000,
                        epsilon_timesteps=5000, training_intensity=8.0)
              .debugging(seed=3))
    algo = config.build()
    for _ in range(40):
        algo.step()
    # Judge the GREEDY policy: behavior-policy returns understate DQN
    # while epsilon is still annealing.
    result = algo.evaluate(num_episodes=5)
    algo.stop()
    assert result["evaluation/episode_return_mean"] > 60, result


def test_sac_pendulum_improves():
    from ray_tpu.rl.algorithms import SACConfig

    config = (SACConfig()
              .environment("Pendulum-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=128)
              .training(train_batch_size=128, lr=3e-3,
                        hidden_sizes=(64, 64), training_intensity=32.0,
                        num_steps_sampled_before_learning_starts=500)
              .debugging(seed=0))
    algo = config.build()
    result = {}
    for _ in range(30):
        result = algo.step()
    algo.stop()
    # Random policy on Pendulum averages around -1200; a learning SAC gets
    # well above that in a few thousand steps.
    assert result["episode_return_mean"] > -900, result


def test_impala_cartpole_learns():
    """IMPALA improves clearly over the ~17 random-policy return. (V-trace
    with single-pass SGD is sample-hungry; the reference's envelopes run
    millions of steps — this is the CI-scale version.)"""
    from ray_tpu.rl.algorithms import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=512)
              .training(train_batch_size=512, lr=5e-3, entropy_coeff=0.005,
                        vf_loss_coeff=0.5, grad_clip=10.0, num_sgd_iter=4)
              .debugging(seed=0))
    algo = config.build()
    best = 0.0
    for _ in range(60):
        result = algo.step()
        best = max(best, result.get("episode_return_mean", 0.0))
    algo.stop()
    assert best > 25, best


def test_appo_cartpole_learns():
    from ray_tpu.rl.algorithms import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=1024)
              .training(train_batch_size=1024, lr=3e-3, entropy_coeff=0.01,
                        vf_loss_coeff=0.5, grad_clip=10.0, num_sgd_iter=12)
              .debugging(seed=0))
    algo = config.build()
    best = 0.0
    for _ in range(30):
        result = algo.step()
        best = max(best, result.get("episode_return_mean", 0.0))
    algo.stop()
    assert best > 40, best


@pytest.mark.usefixtures("ray_start_regular")
def test_appo_async_remote_runners():
    """APPO with remote runners: async in-flight sampling keeps working
    across steps and the policy updates (weights actually change)."""
    from ray_tpu.rl.algorithms import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=64)
              .training(train_batch_size=128)
              .debugging(seed=0))
    algo = config.build()
    w0 = np.asarray(
        algo.learner_group.get_weights()["pi"]["layers"][0]["w"]).copy()
    trained = 0
    for _ in range(6):
        r = algo.step()
        trained += r.get("num_env_steps_trained", 0)
    w1 = np.asarray(
        algo.learner_group.get_weights()["pi"]["layers"][0]["w"])
    algo.stop()
    assert trained > 0
    assert not np.allclose(w0, w1)


def test_bc_clones_expert_policy():
    """BC on synthetic 'expert' data (action = sign of obs feature) reaches
    high logp on the expert action."""
    from ray_tpu.rl.algorithms import BCConfig

    rng = np.random.default_rng(0)
    episodes = []
    for _ in range(20):
        ep = SingleAgentEpisode()
        obs = rng.normal(size=(26, 4)).astype(np.float32)
        ep.add_reset(obs[0])
        for t in range(25):
            a = int(obs[t][0] > 0)
            ep.add_step(obs[t + 1], a, 1.0, terminated=t == 24)
        episodes.append(ep)

    config = (BCConfig()
              .environment("CartPole-v1")
              .offline_data(input_episodes=episodes)
              .training(train_batch_size=128, num_sgd_iter=32, lr=3e-3))
    algo = config.build()
    result = {}
    for _ in range(10):
        result = algo.step()
    algo.stop()
    # Expert is deterministic: cloned logp should approach 0 (prob → 1).
    assert result["bc_logp"] > -0.25, result


def test_marwil_beta_weights_advantages():
    """MARWIL with beta>0 upweights high-return actions: on data where
    action 1 always yields reward 1 and action 0 yields 0, the learned
    policy prefers action 1."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl.algorithms import MARWILConfig

    rng = np.random.default_rng(1)
    episodes = []
    for _ in range(16):
        ep = SingleAgentEpisode()
        obs = rng.normal(size=(21, 4)).astype(np.float32)
        ep.add_reset(obs[0])
        for t in range(20):
            a = int(rng.random() < 0.5)  # behavior: uniform random
            ep.add_step(obs[t + 1], a, float(a), terminated=t == 19)
        episodes.append(ep)

    config = (MARWILConfig()
              .environment("CartPole-v1")
              .offline_data(input_episodes=episodes)
              # gamma=0 → return == immediate reward == the action taken,
              # so the advantage signal is exactly the action choice.
              .training(train_batch_size=128, num_sgd_iter=32, lr=3e-3,
                        beta=2.0, gamma=0.0))
    algo = config.build()
    for _ in range(8):
        algo.step()
    params = algo.learner_group.get_weights()
    obs = rng.normal(size=(64, 4)).astype(np.float32)
    di, _ = rl_module.forward(params, jnp.asarray(obs))
    probs = np.asarray(jax.nn.softmax(di, axis=-1))
    algo.stop()
    assert probs[:, 1].mean() > 0.7, probs[:, 1].mean()


@pytest.mark.usefixtures("ray_start_regular")
def test_offline_dataset_roundtrip_and_bc_learns_from_file(tmp_path):
    """episodes -> parquet transition dataset -> episodes is lossless
    (block order independent), and BC trained from the written FILE
    recovers the expert action mapping (VERDICT r3 item 5; reference
    rllib/offline over ray.data)."""
    import numpy as np

    from ray_tpu.rl.algorithms import BCConfig
    from ray_tpu.rl.episode import SingleAgentEpisode
    from ray_tpu.rl.offline import (
        read_offline_episodes,
        write_offline_dataset,
    )

    rng = np.random.default_rng(3)
    episodes = []
    for i in range(30):
        ep = SingleAgentEpisode(id=f"ep-{i}")
        obs = rng.normal(size=4).astype(np.float32)
        ep.add_reset(obs)
        for t in range(12):
            a = int(obs.sum() > 0)  # expert: sign of the obs sum
            obs = rng.normal(size=4).astype(np.float32)
            ep.add_step(obs, a, 1.0, terminated=(t == 11), logp=-0.1)
        episodes.append(ep)

    path = str(tmp_path / "bc-corpus")
    write_offline_dataset(episodes, path, format="parquet")
    back = read_offline_episodes(path)
    assert len(back) == len(episodes)
    by_id = {e.id: e for e in back}
    for ep in episodes:
        got = by_id[ep.id]
        assert got.actions == ep.actions
        assert got.rewards == ep.rewards
        assert got.terminated == ep.terminated
        np.testing.assert_allclose(np.stack(got.obs), np.stack(ep.obs))

    import gymnasium as gym

    class FakeEnv(gym.Env):
        observation_space = gym.spaces.Box(-10, 10, (4,), np.float32)
        action_space = gym.spaces.Discrete(2)

        def reset(self, *, seed=None, options=None):
            return np.zeros(4, np.float32), {}

        def step(self, action):
            return np.zeros(4, np.float32), 0.0, True, False, {}

    config = (BCConfig()
              .environment(env_fn=FakeEnv)
              .training(train_batch_size=128, lr=1e-2)
              .debugging(seed=0))
    config.num_sgd_iter = 40
    config.offline_data(input_path=path)
    algo = config.build()
    algo.step()
    algo.step()

    # The cloned policy reproduces the expert rule on held-out obs.
    import jax
    import jax.numpy as jnp

    spec = algo.env_runner_group.spec
    params = algo.learner_group.get_weights()
    test_obs = rng.normal(size=(256, 4)).astype(np.float32)
    dist_inputs, _ = spec.forward(params, jnp.asarray(test_obs))
    pred = np.asarray(jnp.argmax(dist_inputs, axis=-1))
    expert = (test_obs.sum(axis=1) > 0).astype(int)
    algo.stop()
    assert (pred == expert).mean() > 0.9, (pred == expert).mean()
