"""Cluster span harvest, per-worker resource profiling, and the
straggler/health watchdog (gcs._op_harvest_spans, worker profile
sampler, gcs._Watchdog), plus the static metrics-conformance check."""

import importlib.util
import json
import os
import signal
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import tracing

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Span-ring cursor math (tracing.collect_spans_since)
# ---------------------------------------------------------------------------

def _record_n(n, name="u"):
    for i in range(n):
        tracing.record_span(f"{name}{i}", 1.0 + i, 2.0 + i, force=True)


def test_collect_spans_since_incremental_and_partial():
    tracing.clear_spans()
    _record_n(10)
    out = tracing.collect_spans_since(0, max_spans=4)
    assert [r[3] for r in out["rows"]] == ["u0", "u1", "u2", "u3"]
    assert out["cursor"] == 4 and out["missed"] == 0
    out = tracing.collect_spans_since(out["cursor"], max_spans=100)
    assert len(out["rows"]) == 6 and out["cursor"] == 10
    # Caught up: empty read, cursor stable.
    out = tracing.collect_spans_since(out["cursor"])
    assert out["rows"] == [] and out["cursor"] == 10
    # New spans appear exactly once under the held cursor.
    _record_n(3, name="v")
    out = tracing.collect_spans_since(out["cursor"])
    assert [r[3] for r in out["rows"]] == ["v0", "v1", "v2"]
    tracing.clear_spans()


def test_collect_spans_since_reports_evictions_as_missed(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE_MAX_SPANS", "16")
    tracing.clear_spans()
    tracing.enable_tracing()  # re-reads the env -> resizes the ring
    try:
        _record_n(40)
        out = tracing.collect_spans_since(0, max_spans=100)
        # Ring kept the newest 16; the 24 evicted before our cursor-0
        # read are reported, not silently skipped.
        assert len(out["rows"]) == 16
        assert out["missed"] == 24
        assert out["cursor"] == 40
        assert out["rows"][0][3] == "u24"
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()
        monkeypatch.delenv("RAY_TPU_TRACE_MAX_SPANS")
        tracing.enable_tracing()  # restore default ring capacity
        tracing.disable_tracing()


def test_collect_spans_since_heals_after_ring_clear():
    tracing.clear_spans()
    _record_n(5)
    cur = tracing.collect_spans_since(0)["cursor"]
    assert cur == tracing.span_cursor() == 5
    tracing.clear_spans()  # worker restarted / ring reset: seq rewinds
    out = tracing.collect_spans_since(cur)
    assert out["rows"] == [] and out["cursor"] == 0
    _record_n(2)
    out = tracing.collect_spans_since(out["cursor"])
    assert len(out["rows"]) == 2
    tracing.clear_spans()


def test_span_row_to_dict_expansion():
    row = ["sid", "par", "tid", "nm", 1.0, 2.0, None]
    s = tracing.span_row_to_dict(row)
    assert s == {"span_id": "sid", "parent_id": "par", "trace_id": "tid",
                 "name": "nm", "start": 1.0, "end": 2.0,
                 "attributes": {}}
    # Head ingest extends rows with worker/pid in place.
    row += ["whex", 4242]
    s = tracing.span_row_to_dict(row)
    assert s["worker"] == "whex" and s["pid"] == 4242


# ---------------------------------------------------------------------------
# profile_report frames on the coalescing flusher
# ---------------------------------------------------------------------------

def test_head_frames_collapse_profile_report_run_to_newest():
    from ray_tpu.core.runtime import CoreClient

    items = [
        ("profile_report", {"ts": 1.0, "cpu_percent": 10.0}),
        ("profile_report", {"ts": 2.0, "cpu_percent": 20.0}),
        ("profile_report", {"ts": 3.0, "cpu_percent": 30.0}),
    ]
    frames = [msg for _, msg in CoreClient._head_frames(items)]
    # Point-in-time state: a backlogged run is ONE frame, newest wins.
    assert len(frames) == 1
    assert frames[0] == {"op": "profile_report",
                         "sample": {"ts": 3.0, "cpu_percent": 30.0}}


# ---------------------------------------------------------------------------
# End-to-end: harvest + profiling + dashboard surfaces
# ---------------------------------------------------------------------------

def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_harvest_profile_and_dashboard_surfaces():
    """Driver + workers in one cluster: worker execution spans are
    parent-linked to the driver's trace via shared trace ids, pulled
    through the head (collect_spans), and served by /api/trace,
    /api/spans and /api/profile."""
    rt = ray_tpu.init(num_cpus=4)
    try:
        tracing.enable_tracing()

        @ray_tpu.remote
        def inner(x):
            return x * 2

        @ray_tpu.remote
        def outer(x):
            return ray_tpu.get(inner.remote(x)) + 1

        with tracing.trace_span("e2e-root"):
            assert ray_tpu.get([outer.remote(i) for i in range(2)],
                               timeout=60) == [1, 3]
        local = tracing.get_spans()
        root = next(s for s in local if s["name"] == "e2e-root")
        trace_id = root["trace_id"]
        assert trace_id

        reply = rt.core.client.call(
            {"op": "harvest_spans", "timeout_s": 15.0})
        spans = reply["spans"]
        assert reply["workers_polled"] >= 2
        mine = [s for s in spans if s["trace_id"] == trace_id]
        # Worker-side execution spans joined the driver's trace.
        workers = {s["worker"] for s in mine
                   if s.get("worker")
                   and s["worker"] != rt.core.worker_hex}
        assert len(workers) >= 2, mine
        by_id = {s["span_id"]: s for s in mine}
        for s in local:
            by_id.setdefault(s["span_id"], s)
        # Parent links resolve inside the harvested trace up to the
        # driver's root.
        exec_spans = [s for s in mine if s.get("worker") in workers]
        assert exec_spans
        for s in exec_spans:
            assert s.get("pid"), s
            cur, hops = s, 0
            while cur.get("parent_id") and hops < 10:
                nxt = by_id.get(cur["parent_id"])
                if nxt is None:
                    break
                cur, hops = nxt, hops + 1
            assert cur["span_id"] == root["span_id"], s

        # Sampler: retune fast, then samples from every worker arrive.
        rt.core.client.call({"op": "set_profile_config",
                             "enabled": True, "interval_s": 0.2})
        deadline = time.time() + 20
        prof = {}
        while time.time() < deadline:
            prof = rt.core.client.call({"op": "get_profile"})
            if len(prof.get("workers", {})) >= 2:
                break
            time.sleep(0.3)
        assert len(prof["workers"]) >= 2, prof
        sample = next(iter(prof["workers"].values()))
        for key in ("cpu_percent", "rss_bytes", "queue_depth",
                    "arena_used_bytes", "mem_total_bytes"):
            assert key in sample, sample
        assert prof["watchdog"]["enabled"] is True

        from ray_tpu.dashboard.http_head import Dashboard
        dash = Dashboard(rt)
        try:
            ev = _get_json(f"{dash.url}/api/trace")
            pids = {e.get("pid") for e in ev
                    if e.get("ph") == "X" and e.get("pid", 0) > 3}
            assert pids, "no harvested worker span lanes in /api/trace"
            out = _get_json(
                f"{dash.url}/api/spans?trace_id={trace_id}")
            assert out["spans"]
            assert all(s["trace_id"] == trace_id for s in out["spans"])
            prof2 = _get_json(f"{dash.url}/api/profile")
            assert prof2["workers"]
        finally:
            dash.stop()
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Watchdog: stalled task -> health verdict + counter
# ---------------------------------------------------------------------------

class _WorkerStaller:
    """util/chaos.py-style killer whose `kill` is SIGSTOP: the victim
    worker freezes mid-task (a stall, not a crash)."""

    def __init__(self, pidfile):
        from ray_tpu.util.chaos import ResourceKiller

        outer = self

        class Staller(ResourceKiller):
            def find_target(self):
                try:
                    with open(pidfile) as f:
                        return int(f.read().strip())
                except (OSError, ValueError):
                    return None

            def kill(self, pid):
                os.kill(pid, signal.SIGSTOP)
                outer.stalled = pid
                return True

        self.stalled = None
        self._killer = Staller(interval_s=0.1, max_kills=1)

    def start(self):
        self._killer.start()
        return self

    def stop(self):
        self._killer.stop()
        if self.stalled is not None:
            try:
                os.kill(self.stalled, signal.SIGCONT)
            except OSError:
                pass


def test_watchdog_flags_stalled_task(tmp_path, monkeypatch):
    from ray_tpu.util import flight_recorder

    monkeypatch.setenv("RAY_TPU_WATCHDOG_INTERVAL_S", "0.3")
    monkeypatch.setenv("RAY_TPU_WATCHDOG_MIN_SAMPLES", "3")
    monkeypatch.setenv("RAY_TPU_WATCHDOG_MULTIPLIER", "1.5")
    monkeypatch.setenv("RAY_TPU_WATCHDOG_MIN_AGE_S", "0.4")
    pidfile = str(tmp_path / "victim.pid")
    stopfile = str(tmp_path / "victim.stop")
    rt = ray_tpu.init(num_cpus=4)
    staller = _WorkerStaller(pidfile)
    try:
        wd = rt.control._watchdog
        assert wd is not None and wd.interval_s == 0.3

        @ray_tpu.remote
        def work(pid_path, stop_path):
            if not pid_path:
                return os.getpid()
            with open(pid_path, "w") as f:
                f.write(str(os.getpid()))
            for _ in range(600):  # stalls under SIGSTOP; exits fast
                if os.path.exists(stop_path):
                    return os.getpid()
                time.sleep(0.05)
            return os.getpid()

        # Fast siblings build the completed-duration distribution.
        ray_tpu.get([work.remote("", "") for _ in range(5)], timeout=60)
        victim = work.remote(pidfile, stopfile)
        staller.start()

        deadline = time.time() + 30
        while time.time() < deadline and wd.stragglers_flagged == 0:
            time.sleep(0.2)
        assert wd.stragglers_flagged >= 1, wd.snapshot()
        health = [e for e in flight_recorder.dump()
                  if e.get("category") == "health"
                  and e.get("event") == "straggler"]
        assert health, "no health-lane straggler event recorded"
        assert health[0]["name"].endswith("work")
        snap = next(s for s in metrics_mod.local_snapshots()
                    if s["name"] == "ray_tpu_stragglers_total")
        assert sum(snap["series"].values()) >= 1.0

        staller.stop()  # SIGCONT -> victim sees stopfile and finishes
        with open(stopfile, "w") as f:
            f.write("stop")
        assert ray_tpu.get(victim, timeout=60) == staller.stalled
    finally:
        staller.stop()
        ray_tpu.shutdown()


def test_watchdog_off_switch_removes_detector(monkeypatch):
    monkeypatch.setenv("RAY_TPU_WATCHDOG", "0")
    rt = ray_tpu.init(num_cpus=1)
    try:
        # The scheduling loop's only residue is a None check.
        assert rt.control._watchdog is None
        reply = rt.core.client.call({"op": "get_profile"})
        assert reply["watchdog"] == {"enabled": False}
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Off-head flight recorder: the dashboard merges the head's ring
# ---------------------------------------------------------------------------

def test_flight_recorder_off_head_merge(tmp_path):
    import subprocess
    import sys

    from ray_tpu.core import rpc

    port = 24600 + (os.getpid() % 2000)
    env = dict(os.environ)
    env["RAY_TPU_CONTROL_PORT"] = str(port)
    env["RAY_TPU_GCS_STORE_PATH"] = str(tmp_path / "gcs.journal")
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--head",
         "--num-cpus", "2", "--no-dashboard", "--block"],
        cwd=_REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 45
        while time.time() < deadline:
            try:
                c = rpc.Client(f"127.0.0.1:{port}", connect_timeout=1.0)
                c.call({"op": "ping"}, timeout=3.0)
                c.close()
                break
            except Exception:
                time.sleep(0.3)
        else:
            raise AssertionError("head never came up")
        rt = ray_tpu.init(address=f"127.0.0.1:{port}")
        try:
            assert getattr(rt, "control", None) is None  # off-head

            @ray_tpu.remote
            def ping():
                return 1

            assert ray_tpu.get(ping.remote(), timeout=60) == 1
            from ray_tpu.dashboard.http_head import Dashboard
            dash = Dashboard(rt)
            try:
                out = _get_json(f"{dash.url}/api/flight_recorder")
                # Local ring stats AND the head process's ring, merged.
                assert "head_stats" in out, out.get("stats")
                assert out["head_stats"]["enabled"] is True
                cats = {e.get("category") for e in out["events"]}
                # Scheduler events only exist head-side; wire events
                # only driver-side — both present proves the merge.
                assert "scheduler" in cats and "wire" in cats, cats
            finally:
                dash.stop()
        finally:
            ray_tpu.shutdown()
    finally:
        head.terminate()
        try:
            head.wait(timeout=10)
        except subprocess.TimeoutExpired:
            head.kill()


# ---------------------------------------------------------------------------
# Recorded overhead budget + static metrics conformance
# ---------------------------------------------------------------------------

def test_profiling_overhead_budget():
    bench = os.path.join(_REPO, "PROF_BENCH.json")
    if not os.path.exists(bench):
        pytest.skip("PROF_BENCH.json not generated")
    with open(bench) as f:
        doc = json.load(f)
    row = doc["multi_client_tasks_async"]
    assert row["disabled_ops_s"] > 0 and row["enabled_ops_s"] > 0
    assert doc["harvest_workers_polled"] > 0
    assert doc["profiled_workers"] > 0
    assert doc["watchdog"]["enabled"] is True
    overhead = row["overhead"]
    assert overhead < 0.05, (
        f"harvest+sampler+watchdog overhead {overhead:.1%} exceeds the "
        f"5% budget ({row['enabled_ops_s']:.0f} vs "
        f"{row['disabled_ops_s']:.0f} ops/s)")


def test_metrics_conformance_static_check():
    """Every ray_tpu_* metric referenced in tests/README is registered
    in the source, and every registered one is documented in README."""
    path = os.path.join(_REPO, "scripts", "check_metrics_conformance.py")
    spec = importlib.util.spec_from_file_location(
        "check_metrics_conformance", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    problems = mod.check()
    assert not problems, "\n".join(problems)
