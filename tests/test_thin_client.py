"""Thin-client tests (SURVEY.md §2.2 P13 Ray Client counterpart).

The thin client is proven shm-independent two ways: in-process (its
CoreClient has store=None, so any shm touch would crash) and from a real
separate process connecting over TCP.
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

import ray_tpu

_REPO_ROOT = str(__import__("pathlib").Path(__file__).resolve().parent.parent)


@pytest.fixture
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def test_thin_client_subprocess_end_to_end(cluster):
    """A separate OS process connects with the thin client and runs
    tasks, large-object put/get (inline path), and actors."""
    script = textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("RAY_TPU_CHIPS", "none")
        import numpy as np
        import ray_tpu
        from ray_tpu.util.client import connect

        ctx = connect({cluster.address!r})
        from ray_tpu.core.runtime import get_runtime
        assert get_runtime().core.store is None  # truly thin

        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(2, 3)) == 5

        # Large object: > inline threshold, ships over TCP both ways.
        big = np.arange(300_000, dtype=np.int64)
        ref = ray_tpu.put(big)

        @ray_tpu.remote
        def total(x):
            return int(x.sum())

        assert ray_tpu.get(total.remote(ref)) == int(big.sum())
        # Worker-produced large result read back through fetch_object.
        @ray_tpu.remote
        def make():
            return np.ones(200_000, dtype=np.float64)

        out = ray_tpu.get(make.remote())
        assert out.shape == (200_000,) and float(out.sum()) == 200_000.0

        class Counter:
            def __init__(self):
                self.n = 0
            def incr(self):
                self.n += 1
                return self.n

        C = ray_tpu.remote(Counter)
        c = C.remote()
        assert ray_tpu.get(c.incr.remote()) == 1
        assert ray_tpu.get(c.incr.remote()) == 2
        ctx.disconnect()
        print("THIN_CLIENT_OK")
    """)
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120, cwd=_REPO_ROOT)
    assert "THIN_CLIENT_OK" in proc.stdout, (proc.stdout, proc.stderr)


def test_thin_client_rejects_second_runtime(cluster):
    from ray_tpu.util.client import connect

    with pytest.raises(RuntimeError, match="already active"):
        connect(cluster.address)


def test_fetch_object_op_reads_shm_payload(cluster):
    """fetch_object returns the serialized payload of a shm object (the
    thin client's read path), including spilled objects."""
    big = np.arange(100_000, dtype=np.int64)
    ref = ray_tpu.put(big)
    ray_tpu.wait([ref])
    data = cluster.kv().call({"op": "fetch_object", "obj": ref.hex()})
    assert data is not None
    from ray_tpu.core.serialization import deserialize

    np.testing.assert_array_equal(deserialize(data), big)
    assert cluster.kv().call(
        {"op": "fetch_object", "obj": "00" * 14}) is None
