"""Headline benchmark: sharded transformer training throughput on TPU.

Prints ONE JSON line:
  {"metric": "train_mfu", "value": <fraction>, "unit": "MFU",
   "vs_baseline": <value / 0.40>, ...}

Baseline: the reference has no in-tree tokens/sec numbers (BASELINE.md —
its LLM release tests are pass/fail); the north-star target recorded in
BASELINE.json is >=40% MFU, so vs_baseline = measured_MFU / 0.40.
"""

from __future__ import annotations

import json
import sys
import time


# Peak bf16 FLOP/s per chip by device kind (public TPU specs). Longest
# key wins, so "v5lite"/"v5e" match before the bare "v5" (v5p): PJRT
# reports v5e as "TPU v5 lite", which must NOT take the 459 TF/s v5p
# peak (it under-reported MFU 2.3x).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,
    "v5": 459e12,    # v5p
    "v5p": 459e12,
    "v6e": 918e12,
    "v6": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS[key]
    if device.platform == "cpu":
        return 1e12  # nominal, so the CPU smoke run still prints a line
    return 275e12


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as tfm
    from ray_tpu.parallel.mesh import build_mesh
    from ray_tpu.train.train_state import ShardedTrainStep, default_optimizer

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"

    if on_tpu:
        # Measured on v5e: remat_policy="dots" (save matmul outputs,
        # recompute elementwise) beats full remat and no-remat at this
        # size; batch sweep: b8=42.7%, b10=43.3%, b12=40.1% (spills),
        # b16 OOMs; remat off tops out at 41.6% (b4) and fails >= b6.
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=6144,
            num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=1024,
            remat_policy="dots",
        )
        batch, seq, steps = 10, 1024, 20
    else:  # CPU smoke mode — same code path, tiny shapes
        config = tfm.TransformerConfig.tiny()
        batch, seq, steps = 4, 64, 3

    mesh = build_mesh(axes={"data": len(devices)}, devices=devices)
    ts = ShardedTrainStep(
        config, mesh,
        optimizer=default_optimizer(warmup_steps=10, total_steps=1000))
    state = ts.init(jax.random.key(0))

    rng = np.random.default_rng(0)
    batch_np = {
        "tokens": jnp.asarray(
            rng.integers(0, config.vocab_size, (batch, seq + 1)),
            dtype=jnp.int32)
    }

    # warmup / compile.  NOTE: sync via scalar D2H fetch (float()), not
    # block_until_ready — the latter is a no-op on some PJRT transports.
    state, metrics = ts.step(state, batch_np)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ts.step(state, batch_np)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_sec = tokens / dt
    flops_tok = tfm.flops_per_token(config, seq)
    peak = _peak_flops(devices[0]) * len(devices)
    mfu = tok_per_sec * flops_tok / peak

    print(json.dumps({
        "metric": "train_mfu",
        "value": round(mfu, 4),
        "unit": "MFU",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_sec_per_chip": round(tok_per_sec / len(devices), 1),
        "model_params": tfm.num_params(config),
        "device": getattr(devices[0], "device_kind", devices[0].platform),
        "n_devices": len(devices),
        "final_loss": round(final_loss, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
