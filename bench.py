"""Headline benchmark: sharded transformer training throughput on TPU.

Prints ONE JSON line:
  {"metric": "train_mfu", "value": <fraction>, "unit": "MFU",
   "vs_baseline": <value / 0.40>, ...}

Baseline: the reference has no in-tree tokens/sec numbers (BASELINE.md —
its LLM release tests are pass/fail); the north-star target recorded in
BASELINE.json is >=40% MFU, so vs_baseline = measured_MFU / 0.40.
"""

from __future__ import annotations

import json
import sys
import time


# Peak bf16 FLOP/s per chip by device kind (public TPU specs). Longest
# key wins, so "v5lite"/"v5e" match before the bare "v5" (v5p): PJRT
# reports v5e as "TPU v5 lite", which must NOT take the 459 TF/s v5p
# peak (it under-reported MFU 2.3x).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,
    "v5": 459e12,    # v5p
    "v5p": 459e12,
    "v6e": 918e12,
    "v6": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS[key]
    if device.platform == "cpu":
        return 1e12  # nominal, so the CPU smoke run still prints a line
    return 275e12


def _tpu_config_ladder(tfm):
    """Largest-first configs (VERDICT r2: billion-class params, seq>=2048,
    fsdp on); the bench walks down on OOM so the driver's automated run
    always lands on the biggest model the chip holds.

    v5e (16 GB HBM) sweep at seq 2048, head_dim 128 (flash kernel,
    512x512 tiles), AdamW mu+nu in bf16 (8 B/param of state), fused
    chunked cross-entropy (ops/fused_ce.py — the r2 log_softmax path's
    [tokens, 32000] fp32 buffers + vocab-scatter backward cost ~25% of
    the step):
      879M full-remat + fused CE: b8=54.7% MFU, b12=54.4%, b10=54.0%
        (r2 without fused CE: b6=40.1%); dots_no_mlp b4=51.8%,
        save_attn b8=53.5% — full remat + big batch wins once the CE
        drag is gone.
    """
    ladder = []
    ladder.append(("0.9B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1792, intermediate_size=7168,
        num_layers=16, num_heads=14, num_kv_heads=14, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    ladder.append(("0.9B-b6", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1792, intermediate_size=7168,
        num_layers=16, num_heads=14, num_kv_heads=14, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 6, 2048))
    ladder.append(("0.8B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    ladder.append(("0.5B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    return ladder


def _run_once(config, batch, seq, steps, devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as tfm
    from ray_tpu.parallel.mesh import build_mesh
    from ray_tpu.train.train_state import ShardedTrainStep, default_optimizer

    # fsdp as the device axis: on one chip it is size 1 (pure compute);
    # on a pod slice the same program shards params/opt-state FSDP-style.
    mesh = build_mesh(axes={"fsdp": len(devices)}, devices=devices)
    ts = ShardedTrainStep(
        config, mesh,
        optimizer=default_optimizer(warmup_steps=10, total_steps=1000,
                                    mu_dtype=jnp.bfloat16,
                                    nu_dtype=jnp.bfloat16))
    state = ts.init(jax.random.key(0))

    rng = np.random.default_rng(0)
    batch_np = {
        "tokens": jnp.asarray(
            rng.integers(0, config.vocab_size, (batch, seq + 1)),
            dtype=jnp.int32)
    }

    # warmup / compile.  NOTE: sync via scalar D2H fetch (float()), not
    # block_until_ready — the latter is a no-op on some PJRT transports.
    state, metrics = ts.step(state, batch_np)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ts.step(state, batch_np)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_sec = tokens / dt
    flops_tok = tfm.flops_per_token(config, seq)
    peak = _peak_flops(devices[0]) * len(devices)
    mfu = tok_per_sec * flops_tok / peak
    return mfu, tok_per_sec, final_loss


def _long_context_ladder(tfm):
    """seq-8192 rows (VERDICT r3: MFU must hold >= 0.5 into the
    flash-kernel regime).  Same 0.9B model, 8k context, full remat:
    measured b2 = 0.602 MFU / 15.3k tok/s on v5e (attention FLOPs grow
    with seq, and the flash kernel keeps them on the MXU)."""
    base = dict(vocab_size=32000, hidden_size=1792,
                intermediate_size=7168, num_layers=16, num_heads=14,
                num_kv_heads=14, max_seq_len=8192,
                remat_policy="full", fused_ce=True)
    return [
        ("0.9B-seq8k", tfm.TransformerConfig(**base), 2, 8192),
        ("0.9B-seq8k-b1", tfm.TransformerConfig(**base), 1, 8192),
    ]


def _large_model_ladder(tfm):
    """Largest-model rows.  1.6B with fp32 master weights + AdamW state
    needs 24.5 GB (measured XLA OOM report) — above v5e's 15.75 GB
    usable HBM on ONE chip, so the single-chip ladder tops out at
    ~1.04B (0.509 MFU measured); the 1.6B shape belongs to a 2+ chip
    fsdp mesh (the same program shards it there)."""
    return [
        ("1.0B", tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=7168,
            num_layers=16, num_heads=16, num_kv_heads=16,
            max_seq_len=2048, remat_policy="full", fused_ce=True),
         6, 2048),
    ]


def _run_ladder(ladder, steps, devices):
    """First config that fits wins (OOM walks down)."""
    for name, config, batch, seq in ladder:
        try:
            mfu, tok_per_sec, final_loss = _run_once(
                config, batch, seq, steps, devices)
            return (name, config, batch, seq, mfu, tok_per_sec,
                    final_loss)
        except Exception as e:  # noqa: BLE001 — OOM: walk down
            msg = str(e)
            # The axon remote-compile transport wraps HBM OOMs in an
            # INTERNAL/HTTP 500 error; treat any compile failure as
            # "doesn't fit" and walk down.
            if any(s in msg for s in (
                    "RESOURCE_EXHAUSTED", "Out of memory",
                    "Ran out of memory", "exceeds the",
                    "remote_compile", "HTTP 500")):
                # Full text to stderr: a genuine compiler/transport bug
                # must stay visible, not be silently masked by walking
                # down to a smaller config.
                import traceback

                traceback.print_exc(file=sys.stderr)
                print(f"# {name} did not fit/compile; trying next config",
                      file=sys.stderr)
                continue
            raise
    return None


def _row_json(tfm, devices, result):
    name, config, batch, seq, mfu, tok_per_sec, final_loss = result
    return {
        "model": name,
        "mfu": round(mfu, 4),
        "tokens_per_sec_per_chip": round(tok_per_sec / len(devices), 1),
        "model_params": tfm.num_params(config),
        "seq_len": seq,
        "batch": batch,
        "final_loss": round(final_loss, 4),
    }


def main():
    import jax

    from ray_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"

    if on_tpu:
        headline_ladder = _tpu_config_ladder(tfm)
        extra_ladders = [_long_context_ladder(tfm),
                         _large_model_ladder(tfm)]
        steps = 20
    else:  # CPU smoke mode — same code path, tiny shapes
        headline_ladder = [("tiny", tfm.TransformerConfig.tiny(), 4, 64)]
        extra_ladders = []
        steps = 3

    result = _run_ladder(headline_ladder, steps, devices)
    if result is None:
        print(json.dumps({"metric": "train_mfu", "value": 0.0,
                          "unit": "MFU", "vs_baseline": 0.0,
                          "error": "all configs OOMed"}))
        return 1
    rows = []
    for ladder in extra_ladders:
        try:
            extra = _run_ladder(ladder, steps, devices)
        except Exception:  # noqa: BLE001 — extras must never cost the
            # already-measured headline its JSON line (the driver
            # records exactly one line per round).
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra = None
        if extra is not None:
            rows.append(_row_json(tfm, devices, extra))

    mfu = result[4]
    head = _row_json(tfm, devices, result)
    print(json.dumps({
        "metric": "train_mfu",
        "value": round(mfu, 4),
        "unit": "MFU",
        "vs_baseline": round(mfu / 0.40, 4),
        **{k: v for k, v in head.items() if k != "mfu"},
        "device": getattr(devices[0], "device_kind", devices[0].platform),
        "n_devices": len(devices),
        # Long-context + largest-model rows (VERDICT r3 item 7): the
        # headline stays the cross-round-comparable 2048-seq config.
        "extra_rows": rows,
    }))


if __name__ == "__main__":
    sys.exit(main())
