"""Headline benchmark: sharded transformer training throughput on TPU.

Prints ONE JSON line:
  {"metric": "train_mfu", "value": <fraction>, "unit": "MFU",
   "vs_baseline": <value / 0.40>, ...}

Baseline: the reference has no in-tree tokens/sec numbers (BASELINE.md —
its LLM release tests are pass/fail); the north-star target recorded in
BASELINE.json is >=40% MFU, so vs_baseline = measured_MFU / 0.40.
"""

from __future__ import annotations

import json
import sys
import time


# Peak bf16 FLOP/s per chip by device kind (public TPU specs). Longest
# key wins, so "v5lite"/"v5e" match before the bare "v5" (v5p): PJRT
# reports v5e as "TPU v5 lite", which must NOT take the 459 TF/s v5p
# peak (it under-reported MFU 2.3x).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,
    "v5": 459e12,    # v5p
    "v5p": 459e12,
    "v6e": 918e12,
    "v6": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS[key]
    if device.platform == "cpu":
        return 1e12  # nominal, so the CPU smoke run still prints a line
    return 275e12


def _tpu_config_ladder(tfm):
    """Largest-first configs (VERDICT r2: billion-class params, seq>=2048,
    fsdp on); the bench walks down on OOM so the driver's automated run
    always lands on the biggest model the chip holds.

    v5e (16 GB HBM) sweep at seq 2048, head_dim 128 (flash kernel,
    512x512 tiles), AdamW mu+nu in bf16 (8 B/param of state), fused
    chunked cross-entropy (ops/fused_ce.py — the r2 log_softmax path's
    [tokens, 32000] fp32 buffers + vocab-scatter backward cost ~25% of
    the step):
      879M full-remat + fused CE: b8=54.7% MFU, b12=54.4%, b10=54.0%
        (r2 without fused CE: b6=40.1%); dots_no_mlp b4=51.8%,
        save_attn b8=53.5% — full remat + big batch wins once the CE
        drag is gone.
    """
    ladder = []
    ladder.append(("0.9B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1792, intermediate_size=7168,
        num_layers=16, num_heads=14, num_kv_heads=14, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    ladder.append(("0.9B-b6", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1792, intermediate_size=7168,
        num_layers=16, num_heads=14, num_kv_heads=14, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 6, 2048))
    ladder.append(("0.8B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    ladder.append(("0.5B", tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=2048,
        remat_policy="full", fused_ce=True,
    ), 8, 2048))
    return ladder


def _run_once(config, batch, seq, steps, devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as tfm
    from ray_tpu.parallel.mesh import build_mesh
    from ray_tpu.train.train_state import ShardedTrainStep, default_optimizer

    # fsdp as the device axis: on one chip it is size 1 (pure compute);
    # on a pod slice the same program shards params/opt-state FSDP-style.
    mesh = build_mesh(axes={"fsdp": len(devices)}, devices=devices)
    ts = ShardedTrainStep(
        config, mesh,
        optimizer=default_optimizer(warmup_steps=10, total_steps=1000,
                                    mu_dtype=jnp.bfloat16,
                                    nu_dtype=jnp.bfloat16))
    state = ts.init(jax.random.key(0))

    rng = np.random.default_rng(0)
    batch_np = {
        "tokens": jnp.asarray(
            rng.integers(0, config.vocab_size, (batch, seq + 1)),
            dtype=jnp.int32)
    }

    # warmup / compile.  NOTE: sync via scalar D2H fetch (float()), not
    # block_until_ready — the latter is a no-op on some PJRT transports.
    state, metrics = ts.step(state, batch_np)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ts.step(state, batch_np)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_sec = tokens / dt
    flops_tok = tfm.flops_per_token(config, seq)
    peak = _peak_flops(devices[0]) * len(devices)
    mfu = tok_per_sec * flops_tok / peak
    return mfu, tok_per_sec, final_loss


def main():
    import jax

    from ray_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"

    if on_tpu:
        ladder = _tpu_config_ladder(tfm)
        steps = 20
    else:  # CPU smoke mode — same code path, tiny shapes
        ladder = [("tiny", tfm.TransformerConfig.tiny(), 4, 64)]
        steps = 3

    result = None
    for name, config, batch, seq in ladder:
        try:
            mfu, tok_per_sec, final_loss = _run_once(
                config, batch, seq, steps, devices)
            result = (name, config, batch, seq, mfu, tok_per_sec,
                      final_loss)
            break
        except Exception as e:  # noqa: BLE001 — OOM: walk down the ladder
            msg = str(e)
            # The axon remote-compile transport wraps HBM OOMs in an
            # INTERNAL/HTTP 500 error; treat any compile failure as
            # "doesn't fit" and walk down.
            if any(s in msg for s in (
                    "RESOURCE_EXHAUSTED", "Out of memory",
                    "Ran out of memory", "exceeds the",
                    "remote_compile", "HTTP 500")):
                # Full text to stderr: a genuine compiler/transport bug
                # must stay visible, not be silently masked by walking
                # down to a smaller config.
                import traceback

                traceback.print_exc(file=sys.stderr)
                print(f"# {name} did not fit/compile; trying next config",
                      file=sys.stderr)
                continue
            raise
    if result is None:
        print(json.dumps({"metric": "train_mfu", "value": 0.0,
                          "unit": "MFU", "vs_baseline": 0.0,
                          "error": "all configs OOMed"}))
        return 1

    name, config, batch, seq, mfu, tok_per_sec, final_loss = result
    print(json.dumps({
        "metric": "train_mfu",
        "value": round(mfu, 4),
        "unit": "MFU",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_sec_per_chip": round(tok_per_sec / len(devices), 1),
        "model_params": tfm.num_params(config),
        "model": name,
        "seq_len": seq,
        "batch": batch,
        "device": getattr(devices[0], "device_kind", devices[0].platform),
        "n_devices": len(devices),
        "final_loss": round(final_loss, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
