// tpustore: node-local shared-memory object arena (plasma counterpart).
//
// Reference counterpart: the plasma store embedded in the raylet
// (reference src/ray/object_manager/plasma/ — ObjectLifecycleManager,
// EvictionPolicy, dlmalloc-on-shm in dlmalloc.cc / shared_memory.cc).
// Design differences, TPU-first:
//   - One sparse shm file per node ("arena") mapped by every process.
//     All metadata (object table, free list, LRU list) lives *inside*
//     the arena, so there is no store server process and no per-request
//     socket round trip: create/get/release are a few hundred ns of
//     shared-memory work under a robust process-shared mutex.  The
//     control plane (object directory, ownership) stays in the GCS.
//   - Object payloads are 64-byte aligned flat buffers so a numpy/jax
//     host array deserialized from the arena aliases shm and can be fed
//     to jax.device_put with zero host copies.
//   - Client accounting: each object's entry tracks per-pid pin counts
//     so a dead worker's pins can be swept (plasma does this with
//     per-connection accounting; we have no connections).
//
// Concurrency: a single robust PTHREAD_PROCESS_SHARED mutex in the
// header serializes metadata updates (matching plasma's single-threaded
// event loop).  Payload reads/writes happen outside the lock.
//
// Exposed as a C ABI consumed from Python via ctypes
// (ray_tpu/native/store.py).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x7470757374307265ULL;  // "tpust0re"
constexpr uint32_t kVersion = 3;
constexpr uint64_t kAlign = 64;        // payload alignment (cache line)
constexpr uint64_t kBlockHdr = 64;     // block header size, keeps data aligned
constexpr int kRefSlots = 24;          // distinct pids pinning one object
constexpr int kIdLen = 20;             // ObjectID bytes

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

struct RefSlot {
  int32_t pid;
  int32_t count;
};

// Object table entry. 256 bytes.
struct Entry {
  uint8_t id[kIdLen];
  uint8_t state;  // see ST_* below
  uint8_t pending_delete;
  uint16_t pad0;
  uint32_t creator_pid;  // pid that created (and may seal) this entry
  uint64_t offset;  // payload offset from arena base
  uint64_t size;    // user payload size
  int64_t lru_prev; // entry index, -1 = none (head side = most recent)
  int64_t lru_next;
  RefSlot refs[kRefSlots];
};
static_assert(sizeof(Entry) == 256, "Entry must be 256 bytes");

// ST_ORPHAN: entry whose id was re-created while old pins were still live
// (task retry re-storing a return object). Unfindable by id — lookups skip
// it like a tombstone — but its block stays allocated until the remaining
// pins are swept/released.
enum : uint8_t {
  ST_EMPTY = 0, ST_TOMB = 1, ST_CREATED = 2, ST_SEALED = 3, ST_ORPHAN = 4,
};

// Heap block header, 64 bytes so payloads stay 64-aligned.
struct Block {
  uint64_t size;       // total block size incl. this header
  uint64_t prev_size;  // size of physically-previous block (0 if first)
  uint32_t used;
  uint32_t pad;
  int64_t next_free;   // arena offsets of free-list neighbours, -1 = none
  int64_t prev_free;
  uint8_t reserved[kBlockHdr - 40];
};
static_assert(sizeof(Block) == kBlockHdr, "Block header must be 64 bytes");

struct Header {
  uint64_t magic;
  uint32_t version;
  volatile uint32_t initialized;
  uint64_t capacity;    // whole file size
  uint64_t table_off;
  uint64_t table_cap;   // number of entries, power of two
  uint64_t heap_off;
  uint64_t heap_size;
  uint64_t nobjects;
  uint64_t used_bytes;  // heap bytes in used blocks (incl. headers)
  int64_t lru_head;     // most recently used
  int64_t lru_tail;     // least recently used
  int64_t free_head;    // arena offset of first free block, -1 = none
  uint64_t evicted_bytes;
  uint64_t evict_count;
  uint64_t tomb_count;   // ST_TOMB slots; rehash resets to 0
  pthread_mutex_t mu;
};

struct Store {
  uint8_t* base;
  uint64_t capacity;
  int fd;
  Header* hdr() const { return reinterpret_cast<Header*>(base); }
  Entry* table() const { return reinterpret_cast<Entry*>(base + hdr()->table_off); }
  Block* block_at(uint64_t off) const { return reinterpret_cast<Block*>(base + off); }
};

// ---------------------------------------------------------------------------
// Locking (robust: survives a lock-holder dying mid-operation)

int lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr()->mu);
  if (rc == EOWNERDEAD) {
    // Previous owner died holding the lock. Metadata may be mid-update;
    // plasma would restart the store — we mark consistent and continue,
    // accepting a possible leaked block (swept by sweep()).
    pthread_mutex_consistent(&s->hdr()->mu);
    rc = 0;
  }
  return rc;
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr()->mu); }

// ---------------------------------------------------------------------------
// Free-list allocator (first fit, boundary-tag coalescing)

void freelist_remove(Store* s, Block* b, uint64_t off) {
  Header* h = s->hdr();
  if (b->prev_free >= 0) s->block_at(b->prev_free)->next_free = b->next_free;
  else h->free_head = b->next_free;
  if (b->next_free >= 0) s->block_at(b->next_free)->prev_free = b->prev_free;
  b->next_free = b->prev_free = -1;
}

void freelist_push(Store* s, Block* b, uint64_t off) {
  Header* h = s->hdr();
  b->used = 0;
  b->prev_free = -1;
  b->next_free = h->free_head;
  if (h->free_head >= 0) s->block_at(h->free_head)->prev_free = off;
  h->free_head = static_cast<int64_t>(off);
}

uint64_t heap_end(Header* h) { return h->heap_off + h->heap_size; }

// Allocate a block with at least `need` payload bytes; returns block offset
// or 0 on failure.
uint64_t alloc_block(Store* s, uint64_t need) {
  Header* h = s->hdr();
  uint64_t want = align_up(kBlockHdr + need, kAlign);
  int64_t off = h->free_head;
  while (off >= 0) {
    Block* b = s->block_at(off);
    if (b->size >= want) {
      freelist_remove(s, b, off);
      if (b->size >= want + kBlockHdr + kAlign) {
        // split: remainder becomes a new free block
        uint64_t rem_off = off + want;
        Block* rem = s->block_at(rem_off);
        rem->size = b->size - want;
        rem->prev_size = want;
        rem->used = 0;
        b->size = want;
        uint64_t after = rem_off + rem->size;
        if (after < heap_end(h)) s->block_at(after)->prev_size = rem->size;
        freelist_push(s, rem, rem_off);
      }
      b->used = 1;
      h->used_bytes += b->size;
      return off;
    }
    off = b->next_free;
  }
  return 0;
}

void free_block(Store* s, uint64_t off) {
  Header* h = s->hdr();
  Block* b = s->block_at(off);
  h->used_bytes -= b->size;
  // coalesce with physical next
  uint64_t next_off = off + b->size;
  if (next_off < heap_end(h)) {
    Block* nb = s->block_at(next_off);
    if (!nb->used) {
      freelist_remove(s, nb, next_off);
      b->size += nb->size;
    }
  }
  // coalesce with physical prev
  if (b->prev_size > 0) {
    uint64_t prev_off = off - b->prev_size;
    Block* pb = s->block_at(prev_off);
    if (!pb->used) {
      freelist_remove(s, pb, prev_off);
      pb->size += b->size;
      off = prev_off;
      b = pb;
    }
  }
  uint64_t after = off + b->size;
  if (after < heap_end(h)) s->block_at(after)->prev_size = b->size;
  freelist_push(s, b, off);
}

// ---------------------------------------------------------------------------
// Object table (open addressing, linear probe)

uint64_t id_hash(const uint8_t* id) {
  uint64_t x;
  memcpy(&x, id, 8);
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL; x ^= x >> 33;
  return x;
}

// Find entry for id; returns index or -1. If `for_insert`, returns the
// first insertable slot (empty/tombstone) when the id is absent.
int64_t table_find(Store* s, const uint8_t* id, bool for_insert) {
  Header* h = s->hdr();
  Entry* t = s->table();
  uint64_t mask = h->table_cap - 1;
  uint64_t i = id_hash(id) & mask;
  int64_t insert_at = -1;
  for (uint64_t probes = 0; probes < h->table_cap; ++probes, i = (i + 1) & mask) {
    Entry& e = t[i];
    if (e.state == ST_EMPTY) {
      if (for_insert) return insert_at >= 0 ? insert_at : static_cast<int64_t>(i);
      return -1;
    }
    if (e.state == ST_TOMB) {
      if (for_insert && insert_at < 0) insert_at = static_cast<int64_t>(i);
      continue;
    }
    if (e.state == ST_ORPHAN) continue;  // unfindable; slot NOT reusable
    if (memcmp(e.id, id, kIdLen) == 0) return static_cast<int64_t>(i);
  }
  return for_insert ? insert_at : -1;
}

int total_refs(const Entry& e) {
  int n = 0;
  for (int i = 0; i < kRefSlots; ++i) n += e.refs[i].count;
  return n;
}

// Find this pid's ref slot, or a free one. When all slots are taken,
// reclaim slots whose pid no longer exists (kill(pid, 0) == ESRCH) —
// crashed readers otherwise exhaust the table. Returns -1 if truly full.
int find_ref_slot(Entry& e, int32_t me) {
  int free_slot = -1;
  for (int i = 0; i < kRefSlots; ++i) {
    if (e.refs[i].pid == me) return i;
    if (free_slot < 0 && e.refs[i].count == 0) free_slot = i;
  }
  if (free_slot >= 0) return free_slot;
  for (int i = 0; i < kRefSlots; ++i) {
    if (kill(e.refs[i].pid, 0) != 0 && errno == ESRCH) {
      e.refs[i].pid = 0;
      e.refs[i].count = 0;
      return i;
    }
  }
  return -1;
}

// ---------------------------------------------------------------------------
// LRU list of sealed objects (head = most recent)

void lru_unlink(Store* s, int64_t idx) {
  Header* h = s->hdr();
  Entry* t = s->table();
  Entry& e = t[idx];
  if (e.lru_prev >= 0) t[e.lru_prev].lru_next = e.lru_next;
  else if (h->lru_head == idx) h->lru_head = e.lru_next;
  if (e.lru_next >= 0) t[e.lru_next].lru_prev = e.lru_prev;
  else if (h->lru_tail == idx) h->lru_tail = e.lru_prev;
  e.lru_prev = e.lru_next = -1;
}

void lru_push_front(Store* s, int64_t idx) {
  Header* h = s->hdr();
  Entry* t = s->table();
  Entry& e = t[idx];
  e.lru_prev = -1;
  e.lru_next = h->lru_head;
  if (h->lru_head >= 0) t[h->lru_head].lru_prev = idx;
  h->lru_head = idx;
  if (h->lru_tail < 0) h->lru_tail = idx;
}

void entry_clear(Store* s, int64_t idx) {
  Entry& e = s->table()[idx];
  lru_unlink(s, idx);
  memset(&e, 0, sizeof(Entry));
  e.state = ST_TOMB;
  s->hdr()->nobjects--;
  s->hdr()->tomb_count++;
}

// Free an object's block and table entry. Caller holds lock.
void drop_object(Store* s, int64_t idx) {
  Entry& e = s->table()[idx];
  if (e.offset > 0) free_block(s, e.offset - kBlockHdr);
  entry_clear(s, idx);
}

// Find an orphaned incarnation of `id` (linear scan; orphans are rare
// and unfindable via probing by design). Returns index or -1.
int64_t find_orphan(Store* s, const uint8_t* id) {
  Header* h = s->hdr();
  Entry* t = s->table();
  for (uint64_t i = 0; i < h->table_cap; ++i) {
    if (t[i].state == ST_ORPHAN && memcmp(t[i].id, id, kIdLen) == 0) {
      return static_cast<int64_t>(i);
    }
  }
  return -1;
}

// Drop one pin held by `pid` on entry idx; applies a deferred/orphan free
// if that was the last pin. Caller holds the lock.
void drop_pin(Store* s, int64_t idx, int32_t pid) {
  Entry& e = s->table()[idx];
  for (int i = 0; i < kRefSlots; ++i) {
    if (e.refs[i].pid == pid && e.refs[i].count > 0) {
      if (--e.refs[i].count == 0) e.refs[i].pid = 0;
      break;
    }
  }
  if (total_refs(e) == 0 &&
      (e.pending_delete || e.state == ST_ORPHAN)) {
    drop_object(s, idx);
  }
}

// Rebuild the object table in place when tombstones dominate, restoring
// O(1) miss lookups (open addressing never un-tombs otherwise). Caller
// holds the lock. LRU order is preserved.
void rehash_table(Store* s) {
  Header* h = s->hdr();
  Entry* t = s->table();
  uint64_t cap = h->table_cap;
  if (h->nobjects >= cap) return;  // no empty slot to reinsert into

  // snapshot live entries + the LRU order (as positions into the snapshot)
  uint64_t nlive = 0;
  for (uint64_t i = 0; i < cap; ++i) {
    if (t[i].state >= ST_CREATED) nlive++;
  }
  Entry* live = new (std::nothrow) Entry[nlive ? nlive : 1];
  int64_t* old_to_live = new (std::nothrow) int64_t[cap];
  if (!live || !old_to_live) {  // allocation failed: skip, try next time
    delete[] live;
    delete[] old_to_live;
    return;
  }
  uint64_t n = 0;
  for (uint64_t i = 0; i < cap; ++i) {
    old_to_live[i] = -1;
    if (t[i].state >= ST_CREATED) {
      live[n] = t[i];
      old_to_live[i] = static_cast<int64_t>(n);
      n++;
    }
  }
  // LRU chain as snapshot positions, head first
  int64_t* lru_order = new (std::nothrow) int64_t[nlive ? nlive : 1];
  uint64_t nlru = 0;
  if (lru_order) {
    for (int64_t idx = h->lru_head; idx >= 0; idx = t[idx].lru_next) {
      lru_order[nlru++] = old_to_live[idx];
    }
  }

  // clear only previously-used slots (a full memset would commit every
  // sparse page of the table)
  for (uint64_t i = 0; i < cap; ++i) {
    if (t[i].state != ST_EMPTY) memset(&t[i], 0, sizeof(Entry));
  }
  h->tomb_count = 0;
  h->lru_head = h->lru_tail = -1;

  // reinsert at canonical probe positions
  int64_t* live_to_new = old_to_live;  // reuse allocation, reindexed by live pos
  uint64_t mask = cap - 1;
  for (uint64_t k = 0; k < n; ++k) {
    uint64_t i = id_hash(live[k].id) & mask;
    while (t[i].state != ST_EMPTY) i = (i + 1) & mask;
    t[i] = live[k];
    t[i].lru_prev = t[i].lru_next = -1;
    live_to_new[k] = static_cast<int64_t>(i);
  }
  // rebuild LRU links in the preserved order (head = most recent): push
  // back-to-front so lru_push_front reconstructs the original chain
  if (lru_order) {
    for (uint64_t k = nlru; k > 0; --k) {
      lru_push_front(s, live_to_new[lru_order[k - 1]]);
    }
  }
  delete[] live;
  delete[] old_to_live;
  delete[] lru_order;
}

void maybe_rehash(Store* s) {
  Header* h = s->hdr();
  if (h->tomb_count > h->table_cap / 2) rehash_table(s);
}

// Evict the single least-recently-used sealed, unpinned object.
// Returns bytes freed (0 if no evictable object exists).
uint64_t evict_one(Store* s) {
  Header* h = s->hdr();
  int64_t idx = h->lru_tail;
  while (idx >= 0) {
    Entry& e = s->table()[idx];
    int64_t prev = e.lru_prev;
    if (e.state == ST_SEALED && total_refs(e) == 0 && !e.pending_delete) {
      uint64_t freed = e.size + kBlockHdr;
      h->evicted_bytes += e.size;
      h->evict_count++;
      drop_object(s, idx);
      return freed;
    }
    idx = prev;
  }
  return 0;
}

// Evict LRU victims until at least `need` heap bytes were freed (or no
// victims remain). Returns bytes freed.
uint64_t evict_lru(Store* s, uint64_t need) {
  uint64_t freed = 0;
  while (freed < need) {
    uint64_t got = evict_one(s);
    if (got == 0) break;
    freed += got;
  }
  return freed;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI

extern "C" {

// Open (and if `create`, initialize) the arena at `path` with `capacity`
// bytes total. Returns an opaque handle or null (errno set).
void* tps_open(const char* path, uint64_t capacity, int create) {
  int fd = -1;
  bool initializer = false;
  if (create) {
    fd = open(path, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd >= 0) {
      initializer = true;
      if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
        close(fd);
        unlink(path);
        return nullptr;
      }
    } else if (errno != EEXIST) {
      return nullptr;
    }
  }
  if (fd < 0) {
    fd = open(path, O_RDWR);
    if (fd < 0) return nullptr;
    // The creator truncates right after its O_EXCL open; wait out the
    // window where the file still has size 0 so concurrent openers don't
    // fail mmap and silently fall back to a different store layout.
    struct stat st;
    uint64_t sz = 0;
    for (int i = 0; i < 100000; ++i) {
      if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
      sz = static_cast<uint64_t>(st.st_size);
      if (sz > 0) break;
      usleep(100);
    }
    if (sz == 0) { errno = EPROTO; close(fd); return nullptr; }
    capacity = sz;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }

  Store* s = new (std::nothrow) Store{static_cast<uint8_t*>(base), capacity, fd};
  if (!s) { munmap(base, capacity); close(fd); return nullptr; }
  Header* h = s->hdr();

  if (initializer) {
    memset(h, 0, sizeof(Header));
    h->magic = kMagic;
    h->version = kVersion;
    h->capacity = capacity;
    // Size the table at one entry per 32 KiB of heap, min 4096, pow2.
    uint64_t want_entries = capacity / (32 * 1024);
    uint64_t cap = 4096;
    while (cap < want_entries) cap <<= 1;
    h->table_cap = cap;
    h->table_off = align_up(sizeof(Header), kAlign);
    uint64_t table_bytes = cap * sizeof(Entry);
    h->heap_off = align_up(h->table_off + table_bytes, kAlign);
    if (h->heap_off + kBlockHdr + kAlign > capacity) {
      errno = EINVAL;  // capacity too small for metadata
      delete s;
      munmap(base, capacity);
      close(fd);
      unlink(path);
      return nullptr;
    }
    h->heap_size = capacity - h->heap_off;
    h->lru_head = h->lru_tail = -1;
    // one big free block spanning the heap
    Block* b = s->block_at(h->heap_off);
    memset(b, 0, sizeof(Block));
    b->size = h->heap_size;
    b->prev_size = 0;
    b->next_free = b->prev_free = -1;
    h->free_head = static_cast<int64_t>(h->heap_off);

    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &attr);
    pthread_mutexattr_destroy(&attr);
    __atomic_store_n(&h->initialized, 1, __ATOMIC_RELEASE);
  } else {
    // wait for the initializer to finish (bounded spin)
    for (int i = 0; i < 100000; ++i) {
      if (__atomic_load_n(&h->initialized, __ATOMIC_ACQUIRE) == 1) break;
      usleep(100);
    }
    if (h->magic != kMagic || h->version != kVersion ||
        __atomic_load_n(&h->initialized, __ATOMIC_ACQUIRE) != 1) {
      errno = EPROTO;
      delete s;
      munmap(base, capacity);
      close(fd);
      return nullptr;
    }
  }
  return s;
}

void tps_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (!s) return;
  munmap(s->base, s->capacity);
  close(s->fd);
  delete s;
}

uint64_t tps_capacity(void* handle) {
  return static_cast<Store*>(handle)->hdr()->capacity;
}

// Create an unsealed object; writes payload offset to *out_off.
// Returns 0, or -EEXIST / -ENOMEM / -ENOSPC (table full).
int tps_create(void* handle, const uint8_t* id, uint64_t size,
               uint64_t* out_off, int evict_ok) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t existing = table_find(s, id, false);
  if (existing >= 0) {
    Entry& old = s->table()[existing];
    if (old.pending_delete) {
      // Deleted-but-pinned (readers hold process-lifetime pins): orphan the
      // old entry so the id becomes insertable; its block is reclaimed when
      // the pins drop (sweep/release).
      lru_unlink(s, existing);
      if (total_refs(old) == 0) {
        drop_object(s, existing);
      } else {
        old.state = ST_ORPHAN;
      }
    } else {
      unlock(s);
      return -EEXIST;
    }
  }
  int64_t idx = table_find(s, id, true);
  if (idx < 0) { unlock(s); return -ENOSPC; }

  uint64_t block = alloc_block(s, size);
  while (block == 0 && evict_ok) {
    // evict one victim at a time and retry, so recently-used objects
    // survive when a smaller eviction suffices
    if (evict_one(s) == 0) break;
    block = alloc_block(s, size);
  }
  if (block == 0) { unlock(s); return -ENOMEM; }

  // only now is the slot actually consumed (an -ENOMEM above must leave
  // the tombstone, and its count, untouched)
  if (s->table()[idx].state == ST_TOMB) s->hdr()->tomb_count--;
  Entry& e = s->table()[idx];
  memset(&e, 0, sizeof(Entry));
  memcpy(e.id, id, kIdLen);
  e.state = ST_CREATED;
  e.offset = block + kBlockHdr;
  e.size = size;
  e.lru_prev = e.lru_next = -1;
  // pin for the creating process so the writer's buffer can't be evicted
  e.creator_pid = static_cast<uint32_t>(getpid());
  e.refs[0].pid = static_cast<int32_t>(getpid());
  e.refs[0].count = 1;
  s->hdr()->nobjects++;
  *out_off = e.offset;
  unlock(s);
  return 0;
}

// Seal a created object (makes it gettable) and drop the creator's pin.
int tps_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t idx = table_find(s, id, false);
  if (idx < 0) { unlock(s); return -ENOENT; }
  Entry& e = s->table()[idx];
  if (e.state == ST_SEALED) { unlock(s); return 0; }
  int32_t me = static_cast<int32_t>(getpid());
  if (e.creator_pid != static_cast<uint32_t>(me)) {
    // The id was re-created by another process (task retry orphaned our
    // entry): their in-flight object is not ours to publish. Drop our
    // creation pin on the orphaned incarnation so its block can free.
    int64_t orphan = find_orphan(s, id);
    if (orphan >= 0 &&
        s->table()[orphan].creator_pid == static_cast<uint32_t>(me)) {
      drop_pin(s, orphan, me);
    }
    unlock(s);
    return 0;
  }
  e.state = ST_SEALED;
  for (int i = 0; i < kRefSlots; ++i) {
    if (e.refs[i].pid == me && e.refs[i].count > 0) {
      if (--e.refs[i].count == 0) e.refs[i].pid = 0;
      break;
    }
  }
  lru_push_front(s, idx);
  unlock(s);
  return 0;
}

// Pin + locate a sealed object. Returns 0 with *out_off/*out_size set,
// or -ENOENT.
int tps_get(void* handle, const uint8_t* id, uint64_t* out_off,
            uint64_t* out_size) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t idx = table_find(s, id, false);
  if (idx < 0 || s->table()[idx].state != ST_SEALED) {
    unlock(s);
    return -ENOENT;
  }
  Entry& e = s->table()[idx];
  int32_t me = static_cast<int32_t>(getpid());
  int slot = find_ref_slot(e, me);
  if (slot < 0) { unlock(s); return -EBUSY; }  // too many live pinners
  e.refs[slot].pid = me;
  e.refs[slot].count++;
  lru_unlink(s, idx);
  lru_push_front(s, idx);
  *out_off = e.offset;
  *out_size = e.size;
  unlock(s);
  return 0;
}

// Copy a sealed object's payload into `dest` while holding the store lock
// (no pin taken; safe because delete/evict also require the lock). Fallback
// for readers that cannot get a pin slot (-EBUSY from tps_get). Returns the
// payload size, -ENOENT if absent, or -ERANGE if dest_len is too small.
int64_t tps_read(void* handle, const uint8_t* id, uint8_t* dest,
                 uint64_t dest_len) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t idx = table_find(s, id, false);
  if (idx < 0 || s->table()[idx].state != ST_SEALED) {
    unlock(s);
    return -ENOENT;
  }
  Entry& e = s->table()[idx];
  if (e.size > dest_len) { unlock(s); return -ERANGE; }
  uint64_t off = e.offset;
  int64_t n = static_cast<int64_t>(e.size);
  int32_t me = static_cast<int32_t>(getpid());
  int slot = find_ref_slot(e, me);
  if (slot >= 0) {
    // pin, copy outside the lock (a multi-GB memcpy must not stall the
    // whole node), then unpin
    e.refs[slot].pid = me;
    e.refs[slot].count++;
    unlock(s);
    memcpy(dest, s->base + off, static_cast<size_t>(n));
    if (lock(s) != 0) return n;  // copied fine; pin swept later
    int64_t idx2 = table_find(s, id, false);
    if (idx2 >= 0 && s->table()[idx2].offset == off) {
      drop_pin(s, idx2, me);
    } else {
      // the id was deleted+re-created while we copied: our pin lives on
      // the orphaned incarnation (matched by payload offset), not on the
      // new entry
      int64_t orphan = find_orphan(s, id);
      if (orphan >= 0 && s->table()[orphan].offset == off) {
        drop_pin(s, orphan, me);
      }
    }
    unlock(s);
    return n;
  }
  // no slot free (the very case this fallback serves): copy under lock
  memcpy(dest, s->base + off, static_cast<size_t>(n));
  unlock(s);
  return n;
}

int tps_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return 0;
  int64_t idx = table_find(s, id, false);
  int ok = idx >= 0 && s->table()[idx].state == ST_SEALED &&
           !s->table()[idx].pending_delete;
  unlock(s);
  return ok;
}

// Drop one pin held by this process. Frees the object if a delete was
// pending and this was the last pin.
int tps_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t idx = table_find(s, id, false);
  if (idx < 0) { unlock(s); return -ENOENT; }
  Entry& e = s->table()[idx];
  int32_t me = static_cast<int32_t>(getpid());
  for (int i = 0; i < kRefSlots; ++i) {
    if (e.refs[i].pid == me && e.refs[i].count > 0) {
      if (--e.refs[i].count == 0) e.refs[i].pid = 0;
      break;
    }
  }
  if (e.pending_delete && total_refs(e) == 0) drop_object(s, idx);
  maybe_rehash(s);
  unlock(s);
  return 0;
}

// Delete an object: immediately if unpinned, else deferred to the last
// release (plasma's deletion semantics).
int tps_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return -EAGAIN;
  int64_t idx = table_find(s, id, false);
  if (idx < 0) { unlock(s); return -ENOENT; }
  Entry& e = s->table()[idx];
  if (total_refs(e) == 0) drop_object(s, idx);
  else e.pending_delete = 1;
  maybe_rehash(s);
  unlock(s);
  return 0;
}

// Evict up to `need` bytes of LRU unpinned sealed objects.
uint64_t tps_evict(void* handle, uint64_t need) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return 0;
  uint64_t freed = evict_lru(s, need);
  unlock(s);
  return freed;
}

// Remove pins held by pids not in `alive` (dead-worker sweep), then apply
// any now-unblocked deferred deletes. Returns number of objects freed.
int tps_sweep(void* handle, const int32_t* alive, int n_alive) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return 0;
  Header* h = s->hdr();
  int freed = 0;
  for (uint64_t i = 0; i < h->table_cap; ++i) {
    Entry& e = s->table()[i];
    if (e.state != ST_CREATED && e.state != ST_SEALED &&
        e.state != ST_ORPHAN) {
      continue;
    }
    for (int r = 0; r < kRefSlots; ++r) {
      if (e.refs[r].count == 0) continue;
      bool ok = false;
      for (int a = 0; a < n_alive; ++a) {
        if (alive[a] == e.refs[r].pid) { ok = true; break; }
      }
      if (!ok) { e.refs[r].pid = 0; e.refs[r].count = 0; }
    }
    if (total_refs(e) == 0 &&
        (e.pending_delete || e.state == ST_CREATED ||
         e.state == ST_ORPHAN)) {
      // dead creator never sealed it, delete was pending, or the id was
      // re-created over this entry and the last pinner is gone
      drop_object(s, static_cast<int64_t>(i));
      freed++;
    }
  }
  maybe_rehash(s);
  unlock(s);
  return freed;
}

void tps_stats(void* handle, uint64_t* capacity, uint64_t* used,
               uint64_t* nobjects, uint64_t* evicted_bytes) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return;
  Header* h = s->hdr();
  if (capacity) *capacity = h->heap_size;
  if (used) *used = h->used_bytes;
  if (nobjects) *nobjects = h->nobjects;
  if (evicted_bytes) *evicted_bytes = h->evicted_bytes;
  unlock(s);
}

}  // extern "C"
