"""End-to-end drive of the observability stack through the real runtime:
metrics (worker publish → driver aggregate → dashboard /metrics scrape),
task timeline, tracing spans, log-to-driver, usage stats, CLI timeline."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import json  # noqa: E402
import time  # noqa: E402
import urllib.request  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.core.runtime import get_runtime  # noqa: E402
from ray_tpu.util import tracing  # noqa: E402


def main():
    t0 = time.time()
    ray_tpu.init(num_cpus=4)
    rt = get_runtime()

    # [1] worker-side user metrics reach the driver aggregation.
    @ray_tpu.remote
    def record(i):
        from ray_tpu.util.metrics import Counter, publish_now

        c = Counter("drive_events", "events", tag_keys=("shard",))
        c.inc(float(i + 1), tags={"shard": str(i)})
        assert publish_now()
        print(f"WORKER_LOG_{i}")
        return i

    assert ray_tpu.get([record.remote(i) for i in range(2)]) == [0, 1]
    from ray_tpu.util.metrics import aggregate_prometheus_text

    text = aggregate_prometheus_text(rt)
    assert 'drive_events{shard="0"} 1.0' in text, text[:500]
    assert 'drive_events{shard="1"} 2.0' in text
    assert "ray_tpu_tasks" in text
    print(f"[1] metrics publish/aggregate ok ({time.time()-t0:.1f}s)")

    # [2] dashboard /metrics + /api/timeline endpoints.
    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(rt)
    scraped = urllib.request.urlopen(dash.url + "/metrics").read().decode()
    assert "drive_events" in scraped
    tl = json.loads(urllib.request.urlopen(dash.url + "/api/timeline").read())
    assert any(e.get("cat") == "task" for e in tl)
    dash.stop()
    print(f"[2] dashboard /metrics + /api/timeline ok ({time.time()-t0:.1f}s)")

    # [3] tracing spans wrap submissions; chrome export merges task slices.
    tracing.enable_tracing()
    with tracing.trace_span("drive-root"):
        ray_tpu.get(record.remote(7))
    spans = tracing.get_spans()
    assert any(s["name"] == "drive-root" for s in spans)
    assert any(s["name"].startswith("submit:") for s in spans)
    out = "/tmp/ray_tpu_drive_trace.json"
    n = tracing.export_chrome_trace(out)
    assert n > len(spans)
    tracing.disable_tracing()
    print(f"[3] tracing spans + chrome export ({n} events) "
          f"({time.time()-t0:.1f}s)")

    # [4] usage stats report lands in the session dir at shutdown.
    import importlib

    importlib.import_module("ray_tpu.data")  # records library usage
    session_dir = rt.session_dir
    ray_tpu.shutdown()
    with open(os.path.join(session_dir, "usage_stats.json")) as f:
        report = json.load(f)
    assert report["counters"].get("library:data"), report
    print(f"[4] usage stats report ok ({time.time()-t0:.1f}s)")

    print("OBSERVABILITY DRIVE OK")


if __name__ == "__main__":
    main()
