"""Drive the LLM inference stack end-to-end: continuous batching,
prefix caching (parity + measured savings), and the serve deployment."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.models import transformer as tfm  # noqa: E402
from ray_tpu.serve.llm_engine import LLMEngine  # noqa: E402


def main():
    config = tfm.TransformerConfig.tiny(
        num_layers=2, num_heads=4, num_kv_heads=2, hidden_size=32,
        intermediate_size=64, vocab_size=64, max_seq_len=256,
        dtype=jnp.float32, use_flash=False, scan_layers=True)
    params = tfm.init_params(config, jax.random.key(0))
    rng = np.random.default_rng(0)

    # Shared system-prompt style workload: one long prefix, many tails.
    prefix = rng.integers(0, 64, size=96).tolist()
    prompts = [prefix + rng.integers(0, 64, size=8).tolist()
               for _ in range(6)]

    cold = LLMEngine(config, params, page_size=16, num_pages=128,
                     max_batch=2, enable_prefix_caching=False)
    t0 = time.perf_counter()
    expected = [cold.generate([p], max_new_tokens=8)[0] for p in prompts]
    t_cold = time.perf_counter() - t0

    warm = LLMEngine(config, params, page_size=16, num_pages=128,
                     max_batch=2, enable_prefix_caching=True)
    t0 = time.perf_counter()
    got = [warm.generate([p], max_new_tokens=8)[0] for p in prompts]
    t_warm = time.perf_counter() - t0

    assert got == expected, "prefix-cached decode diverged from cold"
    saved = warm.prefix_cache.tokens_saved
    assert saved >= 5 * 96, saved  # requests 2..6 reuse the 96-tok prefix
    print(f"[1] prefix caching: parity OK, {saved} prompt tokens skipped, "
          f"{warm.prefix_cache.hits} hits "
          f"(cold {t_cold:.2f}s vs warm {t_warm:.2f}s)")

    # Continuous batching with mixed hit/miss admission.
    out = warm.generate(prompts[:3] + [rng.integers(0, 64, 16).tolist()],
                        max_new_tokens=4)
    assert all(len(o) == 4 for o in out)
    print("[2] continuous batching with mixed cached/uncached admits OK")

    # MoE decoding: greedy engine output == full forward argmax.
    moe_cfg = tfm.TransformerConfig.tiny(
        num_layers=2, num_heads=4, num_kv_heads=2, hidden_size=32,
        intermediate_size=32, vocab_size=64, max_seq_len=64,
        num_experts=4, num_experts_per_token=2, capacity_factor=8.0,
        dtype=jnp.float32, use_flash=False, scan_layers=True)
    moe_params = tfm.init_params(moe_cfg, jax.random.key(1))
    prompt = rng.integers(0, 64, size=9).tolist()
    seq = list(prompt)
    for _ in range(6):
        logits = tfm.forward(moe_params, jnp.asarray([seq]),
                             config=moe_cfg)
        seq.append(int(np.argmax(np.asarray(logits)[0, len(seq) - 1])))
    eng = LLMEngine(moe_cfg, moe_params, page_size=4, num_pages=64,
                    max_batch=2)
    got = eng.generate([prompt], max_new_tokens=6)[0]
    assert got == seq[len(prompt):], (got, seq[len(prompt):])
    print("[3] MoE decode == MoE forward argmax, token for token")

    # Speculative decoding: exact greedy outputs, fewer device steps.
    # (Exactness holds at fp32; bf16 configs could tie-break argmax
    # differently between the verify and decode programs — still a
    # valid greedy continuation, just not bitwise-identical.)
    rep_prompt = ([5, 9, 2, 14] * 10)[:38]
    plain = LLMEngine(config, params, page_size=16, num_pages=128,
                      max_batch=1)
    t0 = time.perf_counter()
    exp = plain.generate([rep_prompt], max_new_tokens=24)[0]
    t_plain = time.perf_counter() - t0
    spec = LLMEngine(config, params, page_size=16, num_pages=128,
                     max_batch=1, speculative_k=6, speculative_ngram=2)
    t0 = time.perf_counter()
    got = spec.generate([rep_prompt], max_new_tokens=24)[0]
    t_spec = time.perf_counter() - t0
    assert got == exp, "speculative decode diverged from plain greedy"
    rate = spec.spec_accepted / max(1, spec.spec_drafted)
    print(f"[4] speculative decode: parity OK, "
          f"{spec.spec_accepted}/{spec.spec_drafted} drafts accepted "
          f"({rate:.0%}), {spec.spec_steps} verify steps for 24 tokens "
          f"(plain {t_plain:.2f}s vs spec {t_spec:.2f}s)")
    print("ALL OK")


if __name__ == "__main__":
    main()
