"""Drive the multi-host plane end-to-end: head + two real node-manager
processes, cross-node object transfer, remote actor, node death.

Run: cd /root/repo && timeout 180 python scripts/verify_drive_multihost.py
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.util.scheduling_strategies import (  # noqa: E402
    NodeAffinitySchedulingStrategy,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def join(address, node_id):
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_manager",
         "--address", address, "--node-id", node_id,
         "--num-cpus", "2", "--num-tpus", "0"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def main():
    rt = ray_tpu.init(num_cpus=1)
    procs = [join(rt.address, "hostA"), join(rt.address, "hostB")]
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            alive = {n["node_id"] for n in rt.state_list("nodes")
                     if n["alive"]}
            if {"hostA", "hostB"} <= alive:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"nodes never joined: {alive}")
        print("[1] two node managers joined:", sorted(alive))

        # soft affinity: places on hostA now (it has free CPUs), but lets
        # lineage reconstruction relocate after hostA dies in step [4]
        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="hostA", soft=True))
        def produce():
            return np.arange(25_000_000, dtype=np.int32)  # 100 MB

        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="hostB"))
        def consume(a):
            return int(a.sum() % 1000003), a.nbytes

        t0 = time.time()
        ref = produce.remote()
        chk, nbytes = ray_tpu.get(consume.remote(ref), timeout=120)
        dt = time.time() - t0
        exp = int(np.arange(25_000_000, dtype=np.int64).sum() % 1000003)
        assert chk == exp and nbytes == 100_000_000, (chk, exp, nbytes)
        print(f"[2] 100MB hostA->hostB transfer + checksum OK in {dt:.2f}s")

        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="hostB"))
        class A:
            def where(self):
                return os.environ.get("RAY_TPU_NODE_ID")

        a = A.remote()
        assert ray_tpu.get(a.where.remote(), timeout=60) == "hostB"
        print("[3] remote-node actor OK")

        rt.core.client.call({"op": "remove_node", "node_id": "hostA"})
        got = ray_tpu.get(ref, timeout=90)  # reconstructed via lineage
        assert got.nbytes == 100_000_000
        print("[4] node death -> lineage reconstruction OK")
        print("ALL OK")
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
