"""Drive the auxiliary subsystems end-to-end: workflow events (incl.
the dashboard HTTP event provider), the serve frame-protocol ingress,
and on-demand worker profiling (stack + jax trace)."""

import json
import os
import socket
import struct
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import ray_tpu  # noqa: E402
from ray_tpu import serve, workflow  # noqa: E402


def drive_workflow_events(rt):
    from ray_tpu.dashboard.http_head import Dashboard

    @ray_tpu.remote
    def double(x):
        return 2 * x

    import uuid as _uuid

    dash = Dashboard(rt)
    try:
        # Unique id + key: workflow storage persists across drive runs,
        # and a checkpointed event step would complete instantly.
        key = f"golive-{_uuid.uuid4().hex[:8]}"
        ev = workflow.wait_for_event(workflow.KVEventListener, key,
                                     poll_interval_s=0.05)
        wid = workflow.run_async(double.bind(ev),
                                 workflow_id=f"wf_drive_{key}")
        time.sleep(0.2)
        assert workflow.get_status(wid) == workflow.WorkflowStatus.RUNNING
        req = urllib.request.Request(
            dash.url + f"/api/events/{key}", data=json.dumps(8).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["status"] == "ok"
        assert workflow.get_output(wid, timeout=30) == 16
        print("[1] workflow event via dashboard HTTP provider -> 16")

        # Profiling through the dashboard route too.
        from ray_tpu.state.api import list_workers
        pool = [w for w in list_workers() if w["kind"] == "pool"]
        target = pool[0]["worker_id"] if pool else rt.core.worker_hex
        with urllib.request.urlopen(
                dash.url + f"/api/workers/{target}/profile?kind=stack",
                timeout=30) as resp:
            prof = json.loads(resp.read())
        assert "Thread" in prof["profile"]
        print(f"[2] stack profile of {target[:8]} via dashboard "
              f"({len(prof['profile'])} chars)")
    finally:
        dash.stop()

    from ray_tpu.state.api import profile_worker
    trace_dir = profile_worker(rt.core.worker_hex, kind="jax_trace",
                               duration_s=0.3)
    assert os.path.isdir(trace_dir)
    print(f"[3] jax xplane trace captured -> {trace_dir}")


def drive_tqdm(rt):
    from ray_tpu.experimental import tqdm_ray

    @ray_tpu.remote
    def work():
        from ray_tpu.experimental import tqdm_ray as tr
        bar = tr.tqdm(desc="drive-bar", total=5)
        for _ in range(5):
            bar.update(1)
            bar.refresh()
            time.sleep(0.05)
        return bar.n  # left open: the driver monitor sees it

    ref = work.remote()
    seen = False
    deadline = time.time() + 20
    while not seen and time.time() < deadline:
        seen = any(b.get("desc") == "drive-bar"
                   for b in tqdm_ray.live_bars().values())
        time.sleep(0.05)
    assert ray_tpu.get(ref) == 5 and seen
    print("[3b] tqdm_ray: worker bar visible from the driver")


def drive_frame_ingress():
    @serve.deployment
    class Api:
        def __call__(self, request):
            return {"doubled": request.json() * 2}

    serve.run(Api.bind(), name="api", route_prefix="/api")
    addr = serve.start_frame_ingress()
    host, port = addr.rsplit(":", 1)
    frame = struct.Struct("<BQI")

    def recv(s, n):
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            assert chunk
            buf += chunk
        return buf

    deadline = time.time() + 20
    while time.time() < deadline:
        s = socket.create_connection((host, int(port)), timeout=10)
        payload = json.dumps({"op": "serve_request", "route": "/api",
                              "payload": 21}).encode()
        s.sendall(frame.pack(3, 1, len(payload)) + payload)
        _, _, length = frame.unpack(recv(s, frame.size))
        reply = json.loads(recv(s, length))
        s.close()
        if reply.get("status") == "ok":
            break
        time.sleep(0.3)
    assert reply == {"status": "ok", "result": {"doubled": 42}}, reply
    print(f"[4] frame-protocol serve ingress at {addr} -> {reply['result']}")
    serve.shutdown()


def main():
    rt = ray_tpu.init(num_cpus=4)
    # Warm a pool worker so the stack profile has a target.
    @ray_tpu.remote
    def warm():
        return 0
    ray_tpu.get(warm.remote())
    drive_workflow_events(rt)
    drive_tqdm(rt)
    drive_frame_ingress()
    ray_tpu.shutdown()
    print("ALL OK")


if __name__ == "__main__":
    main()
