#!/usr/bin/env python
"""bench_index — fold the repo-root bench JSONs into one trajectory.

Every PR that lands a perf-relevant change commits a bench JSON at the
repo root (BENCH_r*, DECODE_BENCH_r*, PROF_BENCH, ...), which makes
the perf trajectory unreadable as a series: ~30 files, each with its
own shape.  This script extracts every headline metric — any node with
a "metric"/"value" pair, any paired-phase "overhead" row, and the
pass/fail multichip probes — into one BENCH_TRAJECTORY.json of
{metric, value, source} rows.

    python scripts/bench_index.py            # writes BENCH_TRAJECTORY.json
    python scripts/bench_index.py --stdout   # print instead

tests/test_bench_index.py pins that every known bench file parses and
that its headline rows survive extraction, so a future bench that
breaks the shape fails the suite instead of silently dropping out of
the trajectory.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Everything bench-shaped the repo root accumulates.  MULTICHIP/SCALE
# predate the *_BENCH naming and are folded in explicitly.
PATTERNS = ("BENCH_r*.json", "*BENCH*.json", "MULTICHIP_r*.json",
            "SCALE_r*.json")

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# Numeric leaves that are headline metrics wherever they appear:
# throughputs, MFU, roofline fractions, kernel speedups.
_HEADLINE_LEAF_RE = re.compile(
    r"(^|_)(ops_s|ops_per_s|per_s|per_sec|per_sec_per_chip|mfu"
    r"|roofline_fraction|speedup_[a-z_]+)$")


def bench_files(root: str = REPO_ROOT) -> List[str]:
    found = set()
    for pat in PATTERNS:
        found.update(glob.glob(os.path.join(root, pat)))
    # The output of this script is not an input to it.
    found.discard(os.path.join(root, "BENCH_TRAJECTORY.json"))
    return sorted(found)


def _round_of(filename: str) -> Optional[int]:
    m = _ROUND_RE.search(filename)
    return int(m.group(1)) if m else None


def _walk(node: Any, path: str, rows: List[Dict[str, Any]],
          source: str) -> None:
    if isinstance(node, dict):
        metric = node.get("metric")
        value = node.get("value")
        if isinstance(metric, str) and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            rows.append({"metric": metric, "value": value,
                         "unit": node.get("unit"), "path": path,
                         "source": source})
        overhead = node.get("overhead")
        if isinstance(overhead, (int, float)) \
                and not isinstance(overhead, bool) and path:
            rows.append({"metric": f"{path}.overhead",
                         "value": overhead, "unit": "fraction",
                         "path": path, "source": source})
        for k, v in node.items():
            if k in ("metric", "value", "unit"):
                continue
            sub = f"{path}.{k}" if path else str(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and _HEADLINE_LEAF_RE.search(k):
                rows.append({"metric": sub, "value": v, "unit": None,
                             "path": path, "source": source})
            _walk(v, sub, rows, source)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            _walk(v, f"{path}[{i}]", rows, source)


def _numeric_leaves(node: Any, path: str, out: List[tuple],
                    limit: int = 16) -> None:
    if len(out) >= limit:
        return
    if isinstance(node, dict):
        for k, v in node.items():
            _numeric_leaves(v, f"{path}.{k}" if path else str(k),
                            out, limit)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            _numeric_leaves(v, f"{path}[{i}]", out, limit)
    elif isinstance(node, (int, float)) and not isinstance(node, bool):
        out.append((path, float(node)))


def extract_rows(doc: Any, source: str) -> List[Dict[str, Any]]:
    """Headline rows of one parsed bench document."""
    rows: List[Dict[str, Any]] = []
    _walk(doc, "", rows, source)
    if not rows:
        # No recognized headline shape (older probe files): keep the
        # file in the trajectory via its first numeric leaves rather
        # than silently dropping it.
        leaves: List[tuple] = []
        _numeric_leaves(doc, "", leaves)
        rows = [{"metric": p, "value": v, "unit": None, "path": p,
                 "source": source} for p, v in leaves]
    if isinstance(doc, dict) and isinstance(doc.get("ok"), bool):
        # Pass/fail probes (MULTICHIP): 1.0/0.0 so they plot.
        rows.append({"metric": "ok", "value": 1.0 if doc["ok"] else 0.0,
                     "unit": "bool", "path": "", "source": source})
    rnd = _round_of(source)
    if rnd is not None:
        for r in rows:
            r["round"] = rnd
    return rows


def build_index(root: str = REPO_ROOT) -> Dict[str, Any]:
    """Parse every bench file under `root` (raises on a file that does
    not parse — the test pins this) and fold the headline rows."""
    files = bench_files(root)
    rows: List[Dict[str, Any]] = []
    sources: List[str] = []
    for path in files:
        name = os.path.basename(path)
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)  # a broken bench file is a failure
        sources.append(name)
        rows.extend(extract_rows(doc, name))
    rows.sort(key=lambda r: (r["metric"], r.get("round") or -1,
                             r["source"]))
    return {"files": sources, "file_count": len(sources),
            "row_count": len(rows), "rows": rows}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fold repo-root bench JSONs into "
                    "BENCH_TRAJECTORY.json.")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--out", default="BENCH_TRAJECTORY.json",
                    help="output filename, relative to --root")
    ap.add_argument("--stdout", action="store_true",
                    help="print the index instead of writing it")
    args = ap.parse_args(argv)
    index = build_index(args.root)
    payload = json.dumps(index, indent=1, sort_keys=False)
    if args.stdout:
        print(payload)
        return 0
    out = os.path.join(args.root, args.out)
    with open(out, "w", encoding="utf-8") as fh:
        fh.write(payload + "\n")
    print(f"{index['row_count']} rows from {index['file_count']} "
          f"files -> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
