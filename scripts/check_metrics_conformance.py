"""Static conformance check for the ray_tpu_* metric namespace.

Docs and tests assert against metric names as plain strings; nothing at
runtime ties those strings to the registration sites in ray_tpu/.  A
renamed counter silently turns a README example stale and can leave a
test asserting on a metric that no longer exists (or worse, passing
because it only checks absence).  This script closes the loop
statically, in both directions:

  1. every `ray_tpu_*` metric token referenced in tests/ or README.md
     must correspond to a metric the source actually registers, and
  2. every metric the source registers must be documented in README.md
     (the Observability section's catalog).

Registrations are extracted from the AST, not regexed, so arbitrary
string literals (file prefixes, contextvar names) don't count:
  - Counter("ray_tpu_...") / Gauge(...) / Histogram(...) registry calls
  - gauge("ray_tpu_...", ...) helper calls in builtin_snapshots
  - {"name": "ray_tpu_...", "kind": ...} snapshot dict literals
  - ("ray_tpu_...", "<description>") 2-tuples (builtin_snapshots'
    node-stat table)

Run: python scripts/check_metrics_conformance.py   (exit 0 = conformant)
Wired into the suite via tests/test_profiling_watchdog.py.
"""

from __future__ import annotations

import ast
import os
import re
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NAME_RE = re.compile(r"\bray_tpu_[a-z0-9_]+\b")
_METRIC_CALLS = {"Counter", "Gauge", "Histogram", "gauge"}

# ray_tpu_* tokens in tests/ that are NOT metric names (shm file
# prefixes, temp dirs, log paths) — keep this list short and literal.
_ALLOWLIST = {
    "ray_tpu_cpp_example",
    "ray_tpu_cpp_worker_example",
    "ray_tpu_shm_example",
    "ray_tpu_test_watchdog",
    "ray_tpu_train_",
}


def _iter_py(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def registered_names() -> set:
    """Metric names the ray_tpu/ source registers or synthesizes."""
    names = set()
    for path in _iter_py(os.path.join(_ROOT, "ray_tpu")):
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                fname = (fn.attr if isinstance(fn, ast.Attribute)
                         else getattr(fn, "id", ""))
                if fname in _METRIC_CALLS and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str) and \
                        node.args[0].value.startswith("ray_tpu_"):
                    names.add(node.args[0].value)
            elif isinstance(node, ast.Dict):
                keys = [k.value for k in node.keys
                        if isinstance(k, ast.Constant)]
                if "name" not in keys or "kind" not in keys:
                    continue
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and \
                            k.value == "name" and \
                            isinstance(v, ast.Constant) and \
                            isinstance(v.value, str) and \
                            v.value.startswith("ray_tpu_"):
                        names.add(v.value)
            elif isinstance(node, ast.Tuple) and len(node.elts) == 2:
                a, b = node.elts
                if isinstance(a, ast.Constant) and \
                        isinstance(a.value, str) and \
                        a.value.startswith("ray_tpu_") and \
                        isinstance(b, ast.Constant) and \
                        isinstance(b.value, str):
                    names.add(a.value)
    return names


def referenced_names() -> dict:
    """{token: [locations]} for ray_tpu_* tokens in tests/ + README."""
    refs: dict = {}
    paths = list(_iter_py(os.path.join(_ROOT, "tests")))
    paths.append(os.path.join(_ROOT, "README.md"))
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, _ROOT)
        for lineno, line in enumerate(text.splitlines(), 1):
            for tok in _NAME_RE.findall(line):
                if tok in _ALLOWLIST:
                    continue
                refs.setdefault(tok, []).append(f"{rel}:{lineno}")
    return refs


def check() -> list:
    """Return a list of problem strings (empty = conformant)."""
    registered = registered_names()
    refs = referenced_names()
    problems = []
    # Histogram expositions append _bucket/_sum/_count; a doc or test
    # may legitimately reference those derived names.
    derived = set()
    for n in registered:
        derived.update({n + "_bucket", n + "_sum", n + "_count"})
    for tok in sorted(refs):
        if tok not in registered and tok not in derived:
            problems.append(
                f"referenced but never registered: {tok} "
                f"({', '.join(refs[tok][:3])})")
    readme_toks = set()
    try:
        with open(os.path.join(_ROOT, "README.md")) as f:
            readme_toks = set(_NAME_RE.findall(f.read()))
    except OSError:
        pass
    for name in sorted(registered):
        if name not in readme_toks:
            problems.append(
                f"registered but undocumented in README.md: {name}")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"METRICS CONFORMANCE: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"ok: {len(registered_names())} registered metric names, "
          f"all references and docs conformant")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
