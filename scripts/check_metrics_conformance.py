"""Static conformance check for the ray_tpu_* metric namespace.

Back-compat shim: the checker moved into the unified static-analysis
suite as the ``conformance`` pass (ray_tpu/analysis/conformance_pass.py
— rules ``metric-unregistered`` / ``metric-undocumented``); run it via
``python -m ray_tpu.analysis --passes conformance``.  This wrapper
keeps the historical CLI and the ``check()`` surface
tests/test_profiling_watchdog.py loads by file path.

Run: python scripts/check_metrics_conformance.py   (exit 0 = conformant)
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from ray_tpu.analysis.conformance_pass import (  # noqa: E402
    metrics_problems,
    referenced_metrics,
    registered_metrics,
)


def registered_names() -> set:
    """Metric names the ray_tpu/ source registers or synthesizes."""
    return set(registered_metrics(_ROOT))


def referenced_names() -> dict:
    """{token: [locations]} for ray_tpu_* tokens in tests/ + README."""
    return {tok: [f"{rel}:{lineno}" for rel, lineno in sites]
            for tok, sites in referenced_metrics(_ROOT).items()}


def check() -> list:
    """Return a list of problem strings (empty = conformant)."""
    return metrics_problems(_ROOT)


def main() -> int:
    problems = check()
    for p in problems:
        print(f"METRICS CONFORMANCE: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"ok: {len(registered_names())} registered metric names, "
          f"all references and docs conformant")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
