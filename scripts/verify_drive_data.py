"""End-to-end drive of the ray_tpu.data public surface (library boundary)."""
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

# The axon sitecustomize re-points jax at the TPU tunnel at interpreter
# start; force the virtual CPU mesh back (same dance as tests/conftest.py).
import jax

jax.config.update("jax_platforms", "cpu")

import ray_tpu
from ray_tpu import data as rd

ray_tpu.init(num_cpus=8)

# read -> fused map chain -> streamed consumption
ds = (rd.range(200, parallelism=8)
      .map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
      .filter(lambda r: r["id"] % 2 == 0))
total = sum(r["sq"] for r in ds.iter_rows())
assert total == sum(i * i for i in range(0, 200, 2)), total
print("[1] read->map->filter streamed:", total)

# all-to-all: shuffle, sort, groupby
items = rd.from_items([{"k": i % 4, "v": float(i)} for i in range(40)])
srt = [r["v"] for r in items.sort("v", descending=True).take_all()]
assert srt == sorted(srt, reverse=True)
g = {r["k"]: r["sum(v)"] for r in items.groupby("k").sum("v").take_all()}
assert len(g) == 4 and sum(g.values()) == sum(range(40))
print("[2] sort + groupby:", g)

# io roundtrip
d = tempfile.mkdtemp()
items.write_parquet(d)
assert rd.read_parquet(d).count() == 40
print("[3] parquet roundtrip ok")

# streaming_split: two concurrent consumers, equalized
its = rd.range(48, parallelism=6).streaming_split(2, equal=True)
got = [0, 0]


def pull(i):
    got[i] = sum(len(b["id"]) for b in its[i].iter_batches(batch_size=8))


ts = [threading.Thread(target=pull, args=(i,)) for i in range(2)]
[t.start() for t in ts]
[t.join(timeout=120) for t in ts]
assert got == [24, 24], got
print("[4] streaming_split equalized:", got)

# device feed: sharded jax arrays over the virtual mesh
import jax

from ray_tpu.parallel.mesh import build_mesh

mesh = build_mesh(axes={"data": len(jax.devices())})
n = 0
for batch in rd.range(64, parallelism=4).iter_device_batches(
        mesh=mesh, batch_size=16):
    assert not batch["id"].is_fully_replicated
    n += int(batch["id"].shape[0])
assert n == 64
print("[5] iter_device_batches sharded over", len(jax.devices()), "devices")

# [6] preprocessors: fit on a dataset, transform streams through workers,
# transform_batch serves single batches with the same stats.
import numpy as np

from ray_tpu.data.preprocessors import Chain, Concatenator, StandardScaler

ds6 = rd.from_items([{"x": float(i), "y": float(i % 3)} for i in range(20)])
chain = Chain(StandardScaler(columns=["x"]),
              Concatenator(columns=["x", "y"])).fit(ds6)
feats = chain.transform(ds6).take_batch(20)["features"]
assert feats.shape == (20, 2)
assert abs(float(np.asarray(feats)[:, 0].mean())) < 1e-5
one = chain.transform_batch({"x": np.array([9.5]), "y": np.array([1.0])})
assert abs(float(one["features"][0, 0])) < 1e-5  # 9.5 = fitted mean
print("[6] preprocessors fit/transform/transform_batch ok")

ray_tpu.shutdown()
print("DATA DRIVE OK")


def drive_images_and_sql():
    """read_images (fixed + variable shape) and read_sql end to end."""
    import sqlite3
    import tempfile

    import numpy as np
    from PIL import Image

    import ray_tpu
    from ray_tpu import data

    ray_tpu.init(num_cpus=2)  # the main drive shut its runtime down
    with tempfile.TemporaryDirectory() as d:
        for i, hw in enumerate([(8, 6), (10, 12), (6, 6)]):
            Image.new("RGB", (hw[1], hw[0]),
                      color=(i * 20, 0, 0)).save(f"{d}/im{i}.png")
        rows = data.read_images(d, mode="RGB").take_all()
        assert sorted(r["image"].shape for r in rows) == \
            [(6, 6, 3), (8, 6, 3), (10, 12, 3)]
        # Fixed-shape path stacks into dense device-ready batches.
        batches = list(data.read_images(d, size=(4, 5), mode="RGB")
                       .iter_batches(batch_size=3))
        assert batches[0]["image"].shape == (3, 4, 5, 3)
        assert batches[0]["image"].dtype == np.uint8

        db = f"{d}/t.db"
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE m (step INTEGER, loss REAL)")
        conn.executemany("INSERT INTO m VALUES (?, ?)",
                         [(i, 5.0 - i) for i in range(4)])
        conn.commit()
        conn.close()
        ds = data.read_sql("SELECT step, loss FROM m ORDER BY step",
                           lambda: sqlite3.connect(db))
        assert ds.count() == 4 and ds.take_all()[-1]["loss"] == 2.0
    print("[images+sql] variable/fixed image reads + SQL rows OK")


def drive_avro_webdataset():
    """Avro OCF + WebDataset tar shards round-trip through the runtime
    (in-tree codecs, no avro/webdataset packages)."""
    import tempfile

    import numpy as np

    from ray_tpu import data

    with tempfile.TemporaryDirectory() as d:
        ds = data.from_items(
            [{"id": i, "name": f"r{i}", "w": 0.5 * i} for i in range(50)])
        files = ds.write_avro(f"{d}/avro")
        back = data.read_avro(files)
        rows = sorted(back.take_all(), key=lambda r: r["id"])
        assert len(rows) == 50 and rows[4]["w"] == 2.0

        wds = data.from_items(
            [{"__key__": f"s{i:03d}", "txt": f"cap {i}", "cls": i,
              "npy": np.arange(3) + i} for i in range(8)])
        shards = wds.write_webdataset(f"{d}/wds")
        out = sorted(data.read_webdataset(shards).take_all(),
                     key=lambda r: r["__key__"])
        assert out[5]["txt"] == "cap 5" and int(out[5]["cls"]) == 5
        np.testing.assert_array_equal(np.asarray(out[5]["npy"]),
                                      np.arange(3) + 5)
    print("[avro+wds] avro OCF + webdataset tar round-trips OK")


drive_images_and_sql()
drive_avro_webdataset()
