"""Control-plane scale probe: locate the head's ceiling on one host.

VERDICT r2 weak #8: the biggest cluster any test exercised was 3 logical
nodes; BASELINE.md's envelope rows are 2,000 nodes / 40k actors / 1M
queued tasks / 1k PGs (on 64-core cloud hosts).  This probe drives the
same four dimensions as far as one host allows and records the rates:

  - logical nodes registered (default 50)
  - queued no-op tasks drained through the scheduler (default 10k)
  - actors created to ALIVE (default 1000 — each actor is a real
    process, so on small hosts the bound is process spawn, not the
    head; the probe records both the rate and that attribution)
  - placement groups created+removed (default 100)

Writes SCALE_r03.json at the repo root.
Usage: python scripts/scale_probe.py [--nodes N] [--tasks N]
       [--actors N] [--pgs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--tasks", type=int, default=10_000)
    ap.add_argument("--actors", type=int, default=1_000)
    ap.add_argument("--pgs", type=int, default=100)
    ap.add_argument("--real-nodes", type=int, default=0,
                    help="also join N REAL node-manager processes so the "
                         "head's resource-view sync (N8) is actively "
                         "broadcasting the full node table while the "
                         "logical nodes churn; the probe records the "
                         "view size a manager serves back")
    ap.add_argument("--big-object-gb", type=float, default=0,
                    help="also put+get one N-GiB object through the shm "
                         "arena (BASELINE.md 'max ray.get numpy object' "
                         "row); sizes the arena to fit")
    ap.add_argument("--out", default="SCALE_r03.json")
    args = ap.parse_args(argv)

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    results: dict = {
        "host_cpus": len(os.sched_getaffinity(0)),
        "targets": {"nodes": args.nodes, "tasks": args.tasks,
                    "actors": args.actors, "pgs": args.pgs},
    }

    # max_workers_per_node clamped so 50 nodes x 64 logical CPUs don't
    # spawn thousands of real worker processes on the probe host; the
    # head's bookkeeping still sees the full logical resource pool.
    sysconf: dict = {"max_workers_per_node": 2}
    if args.big_object_gb:
        # Arena sized to hold the object with headroom; spilling off so
        # the measurement is the shm path, not disk.
        sysconf["object_store_memory"] = int(
            args.big_object_gb * (1 << 30) * 1.25)
        sysconf["object_spilling_threshold"] = 0
    cluster = Cluster(head_node_args={
        "num_cpus": 64, "log_to_driver": False,
        "_system_config": sysconf})

    # -- 0. real node managers (resource-view sync receivers) -------------
    real_procs = []
    try:
        return _probe(args, results, cluster, real_procs)
    finally:
        for p_ in real_procs:
            if p_.poll() is None:
                p_.terminate()


def _probe(args, results, cluster, real_procs) -> int:
    import ray_tpu
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    if args.real_nodes:
        import subprocess

        rt = cluster.runtime
        for i in range(args.real_nodes):
            real_procs.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.node_manager",
                 "--address", rt.address, "--node-id", f"real-{i}",
                 "--num-cpus", "2", "--num-tpus", "0"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.time() + 60
        want = {f"real-{i}" for i in range(args.real_nodes)}
        while time.time() < deadline:
            alive = {n["node_id"] for n in cluster.list_nodes()
                     if n["alive"]}
            if want <= alive:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("real node managers failed to join")

    # -- 1. logical nodes --------------------------------------------------
    t0 = time.perf_counter()
    for i in range(args.nodes - 1):
        cluster.add_node(num_cpus=64, node_id=f"scale-{i}")
    dt = time.perf_counter() - t0
    n_nodes = len(cluster.list_nodes())
    results["nodes"] = {"count": n_nodes,
                        "register_per_s": round((args.nodes - 1) / dt, 1)}
    print(f"nodes: {n_nodes} registered at "
          f"{results['nodes']['register_per_s']}/s", flush=True)

    if args.real_nodes:
        # Prove the synced view propagated the FULL node table to a
        # real manager (debounced broadcast, gcs _sync_resource_view):
        # ask the manager's own server for its cluster view.
        from ray_tpu.core import rpc as _rpc

        mgr_addr = next(n["address"] for n in cluster.list_nodes()
                        if n["node_id"] == "real-0")
        view = None
        deadline = time.time() + 30
        while time.time() < deadline:
            conn = _rpc.Client(mgr_addr, connect_timeout=5.0)
            view = conn.call({"op": "cluster_view"}, timeout=10.0)
            conn.close()
            if view and len(view.get("nodes", view)) >= n_nodes:
                break
            time.sleep(0.5)
        nodes_in_view = len(view.get("nodes", view)) if view else 0
        results["resource_view_sync"] = {
            "receivers": args.real_nodes,
            "nodes_in_synced_view": nodes_in_view,
            "full_table": nodes_in_view >= n_nodes,
        }
        print(f"view sync: manager serves {nodes_in_view} nodes "
              f"(full={results['resource_view_sync']['full_table']})",
              flush=True)

    # -- 2. queued tasks ---------------------------------------------------
    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 0

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(args.tasks)]
    submit_dt = time.perf_counter() - t0
    ray_tpu.get(refs, timeout=3600)
    drain_dt = time.perf_counter() - t0
    results["tasks"] = {
        "queued": args.tasks,
        "submit_per_s": round(args.tasks / submit_dt, 1),
        "drain_per_s": round(args.tasks / drain_dt, 1),
    }
    print(f"tasks: {args.tasks} submitted at "
          f"{results['tasks']['submit_per_s']}/s, drained at "
          f"{results['tasks']['drain_per_s']}/s", flush=True)

    # -- 3. actors ---------------------------------------------------------
    class A:
        def ping(self):
            return 0

    Actor = ray_tpu.remote(A)
    t0 = time.perf_counter()
    actors = [Actor.options(num_cpus=0.01).remote()
              for _ in range(args.actors)]
    # One call per actor proves every one reached ALIVE and answers.
    ray_tpu.get([a.ping.remote() for a in actors], timeout=3600)
    dt = time.perf_counter() - t0
    results["actors"] = {
        "count": args.actors,
        "to_alive_per_s": round(args.actors / dt, 1),
        "note": "each actor is a dedicated OS process; on few-core "
                "hosts this rate is process-spawn-bound, not "
                "head-bound",
    }
    print(f"actors: {args.actors} alive at "
          f"{results['actors']['to_alive_per_s']}/s", flush=True)

    # Tear the actors down so PG timing below is clean.
    t0 = time.perf_counter()
    for a in actors:
        ray_tpu.kill(a)
    results["actors"]["kill_per_s"] = round(
        args.actors / (time.perf_counter() - t0), 1)

    # -- 4. placement groups ----------------------------------------------
    t0 = time.perf_counter()
    pgs = [placement_group([{"CPU": 1}] * 2, strategy="SPREAD")
           for _ in range(args.pgs)]
    ray_tpu.get([pg.ready() for pg in pgs], timeout=600)
    create_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for pg in pgs:
        remove_placement_group(pg)
    remove_dt = time.perf_counter() - t0
    results["placement_groups"] = {
        "count": args.pgs,
        "create_ready_per_s": round(args.pgs / create_dt, 1),
        "remove_per_s": round(args.pgs / remove_dt, 1),
    }
    print(f"pgs: {args.pgs} ready at "
          f"{results['placement_groups']['create_ready_per_s']}/s, "
          f"removed at {results['placement_groups']['remove_per_s']}/s",
          flush=True)

    # -- 5. large single object (opt-in) ----------------------------------
    if args.big_object_gb:
        import mmap

        import numpy as np

        n = int(args.big_object_gb * (1 << 30) // 8)
        arr = np.arange(n, dtype=np.int64)  # real bytes, not COW zeros
        nbytes = n * 8
        # Control: a bare tmpfs mmap write of the SAME byte count —
        # big-object puts are first-touch page-fault bound on virtualized
        # hosts, so the honest framework number is overhead OVER this.
        ctl_path = os.path.join("/dev/shm", f"scale-probe-ctl-{os.getpid()}")
        with open(ctl_path, "w+b") as f:
            f.truncate(nbytes)
            mm = mmap.mmap(f.fileno(), nbytes)
            view = memoryview(mm)
            t0 = time.perf_counter()
            view[:nbytes] = memoryview(arr).cast("B")
            raw_dt = time.perf_counter() - t0
            view.release()
            mm.close()
        os.unlink(ctl_path)
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_dt = time.perf_counter() - t0
        del arr
        t0 = time.perf_counter()
        back = ray_tpu.get(ref, timeout=3600)
        get_dt = time.perf_counter() - t0
        assert int(back[0]) == 0 and int(back[-1]) == n - 1
        gb = nbytes / 1e9
        results["large_object"] = {
            "gigabytes": round(gb, 2),
            "put_s": round(put_dt, 2),
            "put_gb_per_s": round(gb / put_dt, 2),
            "raw_tmpfs_write_s": round(raw_dt, 2),
            "framework_overhead_pct": round(
                max(0.0, put_dt / raw_dt - 1.0) * 100, 1),
            "get_s": round(get_dt, 3),
            "note": "get is a zero-copy view over the shm arena "
                    "(deserialize aliases the segment); "
                    "raw_tmpfs_write_s is a bare mmap write of the same "
                    "byte count on the same host, measured just before "
                    "the put",
        }
        print(f"large object: {gb:.1f} GB put in {put_dt:.1f}s "
              f"(raw tmpfs control {raw_dt:.1f}s -> "
              f"{results['large_object']['framework_overhead_pct']}% "
              f"overhead), get in {get_dt:.3f}s", flush=True)
        del back, ref

    cluster.shutdown()
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
