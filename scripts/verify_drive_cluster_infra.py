"""Drive: autoscaler + state API + jobs + dashboard + CLI address flow."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import json
import time
import urllib.request

import ray_tpu


def main():
    from ray_tpu.autoscaler import (
        Autoscaler, AutoscalerConfig, FakeMultiNodeProvider, NodeTypeConfig)
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})

    @ray_tpu.remote(num_cpus=2)
    def heavy():
        return os.getpid()

    provider = FakeMultiNodeProvider(cluster)
    autoscaler = Autoscaler(
        cluster.runtime.kv().call, provider,
        AutoscalerConfig(node_types={
            "cpu2": NodeTypeConfig({"CPU": 2}, max_workers=2)}))
    ref = heavy.remote()
    time.sleep(0.3)
    launched = autoscaler.step()
    assert launched == {"cpu2": 1}, launched
    assert ray_tpu.get([ref], timeout=30)[0] > 0
    print("[1] autoscaler scaled up for pending demand")

    from ray_tpu import state

    assert any(n["is_head"] for n in state.list_nodes())
    assert state.summarize_tasks()["total"] >= 1
    print("[2] state api ok")

    from ray_tpu.dashboard import Dashboard
    from ray_tpu.job import JobSubmissionClient, JobStatus

    dash = Dashboard(cluster.runtime)
    with urllib.request.urlopen(dash.url + "/api/nodes", timeout=10) as r:
        nodes = json.loads(r.read())
    assert len(nodes) >= 2  # head + autoscaled node
    print("[3] dashboard ok:", dash.url)

    client = JobSubmissionClient()
    jid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('drive job')\"")
    assert client.wait_until_finished(jid, 60) == JobStatus.SUCCEEDED
    assert "drive job" in client.get_job_logs(jid)
    print("[4] job submission ok")

    dash.stop()
    cluster.shutdown()
    print("CLUSTER INFRA DRIVE OK")


if __name__ == "__main__":
    main()
