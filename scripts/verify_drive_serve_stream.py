"""Verify driver: serve data plane at scale, end-to-end.

Covers the streaming/admission surface: JSONL + SSE chunked HTTP
streaming (first chunk before completion), gRPC server streaming,
mid-stream disconnect freeing the engine slot + KV pages, engine
admission backpressure (queue cap + deadline shed), and replica load
reports feeding the router.
"""

import http.client
import json
import os
import sys
import time
from urllib.parse import urlparse

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import ray_tpu  # noqa: E402
from ray_tpu import serve  # noqa: E402


def _read_stream(resp):
    arrivals, raw = [], b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        raw += chunk
        arrivals.append(time.monotonic())
    return raw, arrivals


def main():
    ray_tpu.init(num_cpus=8)
    serve.start()
    t0 = time.time()

    # [1] streaming deployment: JSONL + SSE framing, first chunk early
    @serve.deployment(name="ticker")
    class Ticker:
        def __call__(self, request):
            for i in range(4):
                time.sleep(0.2)
                yield {"tok": i}

    serve.run(Ticker.bind(), name="tick", route_prefix="/tick")
    base = urlparse(serve.proxy_address())
    conn = http.client.HTTPConnection(base.hostname, base.port, timeout=60)
    conn.request("GET", "/tick", headers={"X-Serve-Stream": "1"})
    resp = conn.getresponse()
    assert resp.status == 200, resp.status
    raw, arrivals = _read_stream(resp)
    conn.close()
    lines = [json.loads(x) for x in raw.splitlines() if x]
    assert lines == [{"tok": i} for i in range(4)], lines
    assert arrivals[-1] - arrivals[0] > 0.3, "buffered, not streamed"
    print(f"[1] JSONL stream ok, spread {arrivals[-1]-arrivals[0]:.2f}s")

    conn = http.client.HTTPConnection(base.hostname, base.port, timeout=60)
    conn.request("GET", "/tick", headers={"Accept": "text/event-stream"})
    resp = conn.getresponse()
    assert resp.headers.get("Content-Type") == "text/event-stream"
    raw, _ = _read_stream(resp)
    conn.close()
    events = [f[len(b"data: "):].decode()
              for f in raw.split(b"\n\n") if f.startswith(b"data: ")]
    assert events[-1] == "[DONE]" and json.loads(events[0]) == {"tok": 0}
    print(f"[2] SSE stream ok ({len(events)} frames, [DONE]-terminated)")

    # [3] LLM token streaming + mid-stream disconnect frees slot+pages
    from ray_tpu.serve.llm import LLMServer

    h = serve.run(
        LLMServer.bind(config_kwargs={}, page_size=4, num_pages=64,
                       max_batch=2, enable_prefix_caching=False),
        name="llm", route_prefix="/llm")
    toks = list(h.options(stream=True,
                          method_name="generate_stream").remote([1, 2, 3], 6))
    assert len(toks) == 6, toks
    st0 = h.stats.remote().result(timeout_s=60)
    it = iter(h.options(stream=True,
                        method_name="generate_stream").remote([1, 2, 3], 100))
    next(it)
    it.close()  # disconnect mid-generation
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = h.stats.remote().result(timeout_s=60)
        if st["active"] == 0 and st["free_pages"] == st0["free_pages"]:
            break
        time.sleep(0.2)
    assert st["num_aborted"] >= 1 and st["active"] == 0, st
    assert st["free_pages"] == st0["free_pages"], (st0, st)
    print(f"[3] LLM stream + disconnect ok (aborted={st['num_aborted']}, "
          f"pages recovered {st['free_pages']}/{st['num_pages']})")

    # [4] admission backpressure: queue cap sheds at the door
    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine, QueueFull

    eng = LLMEngine(tfm.TransformerConfig.tiny(), page_size=4,
                    num_pages=64, max_batch=2, max_queue=2,
                    queue_timeout_s=0)
    eng.add_request([1, 2], 4)
    eng.add_request([3, 4], 4)
    try:
        eng.add_request([5, 6], 4)
        raise AssertionError("queue cap did not fire")
    except QueueFull:  # raylint: allow-swallow(asserting the cap fires is the point of this step)
        pass
    print(f"[4] admission backpressure ok (shed={eng.num_shed})")

    # [5] replica load reports reach the router's long-poll key
    from ray_tpu.serve.api import _get_controller

    ctrl = _get_controller()
    key = "load::llm::llm_server"
    reports = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not reports:
        changed = ray_tpu.get(
            ctrl.listen_for_change.remote({key: 0}, 5.0), timeout=15)
        if key in (changed or {}):
            _, reports = changed[key]
    assert reports, "no load report published within 30s"
    rep = next(iter(reports.values()))
    assert "queue_depth" in rep and "free_kv_pages" in rep, rep
    print(f"[5] load report ok: {sorted(rep)}")

    serve.shutdown()
    ray_tpu.shutdown()
    print(f"SERVE STREAM DRIVE OK in {time.time()-t0:.1f}s")


if __name__ == "__main__":
    main()
