"""Drive synchronous HyperBand, chaos killers, and non-blocking
profiling through the public API."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import ray_tpu  # noqa: E402
from ray_tpu import tune  # noqa: E402
from ray_tpu.train import RunConfig  # noqa: E402


def drive_hyperband(run_dir):
    def objective(config):
        for step in range(1, 10):
            tune.report({"score": config["q"] * step})

    grid = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([0.2, 1.0, 3.0, 9.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.HyperBandScheduler(max_t=9,
                                              reduction_factor=3),
            max_concurrent_trials=4),
        run_config=RunConfig(storage_path=run_dir, name="hb"),
    ).fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    assert iters[0] < 9 and iters[-1] == 9, iters
    best = max(r.metrics.get("score", -1) for r in grid)
    assert best == 81.0, best
    print(f"[1] HyperBand: iters={iters} best={best} (culled + survivor)")


def drive_chaos():
    from ray_tpu.util.chaos import WorkerKiller

    @ray_tpu.remote(max_retries=5)
    def square(i):
        time.sleep(0.1)
        return i * i

    killer = WorkerKiller(interval_s=0.4, max_kills=2).start()
    try:
        out = ray_tpu.get([square.remote(i) for i in range(30)],
                          timeout=120)
    finally:
        killer.stop()
    assert out == [i * i for i in range(30)]
    print(f"[2] chaos: 30 tasks survived {len(killer.killed)} worker kill(s)")


def drive_nonblocking_profile():
    """A long trace of one worker must not stall the driver's other
    control-plane calls (Deferred responses on the server)."""
    from ray_tpu.state.api import list_workers, profile_worker

    @ray_tpu.remote
    def nap(s):
        time.sleep(s)
        return s

    ray_tpu.get(nap.remote(0.01))  # warm a pool worker
    target = next(w for w in list_workers()
                  if w["kind"] == "pool" and w["state"] != "dead")
    import threading
    result = {}

    def long_profile():
        result["trace"] = profile_worker(target["worker_id"],
                                         kind="stack", duration_s=0.0)

    t = threading.Thread(target=long_profile)
    t.start()
    # Concurrent control-plane traffic during the profile round-trip.
    t0 = time.time()
    vals = ray_tpu.get([nap.remote(0.05) for _ in range(8)], timeout=60)
    dt = time.time() - t0
    t.join(timeout=60)
    assert vals == [0.05] * 8
    assert "Thread" in result.get("trace", ""), result
    print(f"[3] profile + concurrent tasks ok ({dt:.2f}s for 8 naps)")


def main():
    import tempfile

    rt = ray_tpu.init(num_cpus=4)
    with tempfile.TemporaryDirectory() as d:
        drive_hyperband(d)
    drive_chaos()
    drive_nonblocking_profile()
    ray_tpu.shutdown()
    print("ALL OK")


if __name__ == "__main__":
    main()
