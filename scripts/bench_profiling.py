"""Profiling/watchdog overhead probe: the cluster span harvest, the
per-worker resource sampler, and the straggler watchdog together must
cost < 5% on the control-plane hot path.

Same paired-window methodology as scripts/bench_observability.py (the
`multi_client_tasks_async` shape, interleaved A/B windows, per-round
ratios), measuring the MARGINAL cost of the stack added on top of
tracing: both arms run with tracing enabled (span recording is the
precondition for a harvest, and its own cost is what OBS_BENCH.json
prices); the "enabled" arm additionally runs a fast profile sampler on
every worker (set_profile_config) and a 1 Hz cluster-wide
harvest_spans sweep from a background poller, with the watchdog
ticking head-side throughout.  The "disabled" arm is tracing only.

Writes PROF_BENCH.json at the repo root (tests/test_profiling_watchdog
.py's budget test reads it) and exits nonzero if the paired measurement
shows >= 5% overhead.

Run: python scripts/bench_profiling.py
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

OVERHEAD_BUDGET = 0.05
SAMPLER_INTERVAL_S = 1.0  # 5x the shipped default rate
# Attribution switches (default: full stack on).  RAY_TPU_BENCH_HARVEST=0
# or RAY_TPU_BENCH_SAMPLER=0 drops one component from the enabled arm to
# localize a regression.
_HARVEST = os.environ.get("RAY_TPU_BENCH_HARVEST", "1") != "0"
_SAMPLER = os.environ.get("RAY_TPU_BENCH_SAMPLER", "1") != "0"


def device_phase(rounds: int = 12, drains: int = 4) -> dict:
    """Paired device-telemetry on/off phase: one tiny LLMEngine,
    alternating device_stats enabled (compile hook + roofline/MFU step
    accounting + device.step spans) against disabled.  The telemetry
    rides the engine step path, so its marginal cost shows up there or
    nowhere.  Each measurement is a FIXED unit of work — `drains` full
    admit-to-drain cycles over the same prompts — rather than a
    wall-clock window: identical workloads per arm keep the variance
    down to host jitter, which the per-round A/B ratio then cancels.
    Runs on whatever backend jax picks (CPU in CI); the cost being
    priced is pure host-side bookkeeping."""
    import gc
    import statistics
    import time as _time

    import numpy as np

    os.environ.setdefault("RAY_TPU_SERVE_STEP_SAMPLE_EVERY", "4")
    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine
    from ray_tpu.util import device_stats

    c = tfm.TransformerConfig.tiny()
    eng = LLMEngine(c, page_size=4, num_pages=64, max_batch=4,
                    multi_step=1)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, c.vocab_size, 8).tolist()
               for _ in range(4)]

    def one_drain() -> int:
        for p in prompts:
            eng.add_request(p, max_new_tokens=8)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
        return steps

    one_drain()  # warmup: compile every program first

    def one_measure() -> float:
        # GC pauses landing in one arm but not the other are the main
        # noise source at ~10 ms windows; collect up front, then keep
        # the collector out of the timed region.
        gc.collect()
        gc.disable()
        try:
            start = _time.perf_counter()
            steps = 0
            for _ in range(drains):
                steps += one_drain()
            return steps / (_time.perf_counter() - start)
        finally:
            gc.enable()

    off_rates, on_rates, ratios = [], [], []
    for r in range(rounds):
        order = [(False, off_rates), (True, on_rates)]
        if r % 2:
            order.reverse()
        for on, rates in order:
            device_stats.set_enabled(on)
            rates.append(one_measure())
        ratios.append(on_rates[-1] / off_rates[-1])
    device_stats.set_enabled(True)
    return {
        "off_steps_s": round(statistics.median(off_rates), 1),
        "off_std": round(statistics.stdev(off_rates), 1),
        "on_steps_s": round(statistics.median(on_rates), 1),
        "on_std": round(statistics.stdev(on_rates), 1),
        "overhead": round(1.0 - statistics.median(ratios), 4),
        "sample_every": int(os.environ.get(
            "RAY_TPU_SERVE_STEP_SAMPLE_EVERY", "4")),
        "rounds": rounds,
        "drains_per_window": drains,
    }


def main() -> int:
    import ray_tpu
    from ray_tpu.scripts.microbenchmark import SCALE
    from ray_tpu.util import tracing

    rt = ray_tpu.init(num_cpus=16, log_to_driver=False)

    @ray_tpu.remote
    def small_task():
        return b"ok"

    ray_tpu.get([small_task.remote() for _ in range(16)])

    class TaskClient:
        def run_batch(self, n):
            import ray_tpu as rt_

            rt_.get([small_task.remote() for _ in range(n)])
            return n

    TC = ray_tpu.remote(TaskClient)
    tclients = [TC.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([c.run_batch.remote(1) for c in tclients])
    n = max(50, int(250 * SCALE))

    def multi_tasks():
        ray_tpu.get([c.run_batch.remote(n) for c in tclients])

    import statistics
    import threading
    import time as _time

    head = rt.core.client

    # Background harvester: a dashboard polling /api/trace once a second
    # while the cluster is saturated.  The sweep's control-plane traffic
    # (cursor-incremental span pulls from every worker) competes with the
    # benchmark's task RPCs on the same connections, so its cost shows up
    # as lost throughput in the enabled windows — without billing the
    # sweep's own wall time as if it were on the submit path.
    harvest_on = threading.Event()
    harvester_exit = threading.Event()
    sweeps = [0]

    def _harvester():
        while not harvester_exit.is_set():
            if harvest_on.is_set():
                try:
                    # Bounded reply: the sweep (pulling every worker's
                    # ring into the head store) is the recurring cost
                    # being measured; shipping the whole accumulated
                    # store back is the on-demand /api/trace action,
                    # not something a poller does at 1 Hz.
                    head.call({"op": "harvest_spans", "max_spans": 256,
                               "timeout_s": 10.0})
                    sweeps[0] += 1
                except Exception:
                    pass
            # 0.5 Hz: a dashboard auto-refresh cadence.  The sweep is
            # cursor-incremental, so a slower poll moves the same spans
            # in fewer, larger rounds — less per-round overhead.
            harvester_exit.wait(2.0)

    threading.Thread(target=_harvester, name="bench-harvester",
                     daemon=True).start()

    def set_stack(on: bool):
        # Tracing stays on in BOTH arms (it is the harvested data
        # source; OBS_BENCH.json prices it separately) — the toggle is
        # the sampler, cluster-wide through the head's
        # set_profile_config broadcast, plus the harvest poller.
        (harvest_on.set if (on and _HARVEST)
         else harvest_on.clear)()
        try:
            head.call({"op": "set_profile_config",
                       "enabled": on and _SAMPLER,
                       "interval_s": SAMPLER_INTERVAL_S})
        except Exception:
            pass

    def one_window(window_s: float = 3.0) -> float:
        start = _time.perf_counter()
        count = 0
        while _time.perf_counter() - start < window_s:
            multi_tasks()
            count += 1
        return count * 4 * n / (_time.perf_counter() - start)

    assert not tracing.is_tracing_enabled()
    tracing.enable_tracing()
    multi_tasks()  # warmup
    dis_rates, en_rates, ratios = [], [], []
    for r in range(10):
        # Alternate which mode goes first (same drift-cancelling A/B
        # pairing as bench_observability.py).
        order = [(False, dis_rates), (True, en_rates)]
        if r % 2:
            order.reverse()
        for on, rates in order:
            set_stack(on)
            # Settle: an async sweep started in the previous window
            # must not straddle into this one's timing.
            _time.sleep(0.3)
            rates.append(one_window())
        ratios.append(en_rates[-1] / dis_rates[-1])
    harvester_exit.set()
    harvest = {}
    try:
        harvest = head.call({"op": "harvest_spans", "timeout_s": 10.0})
    except Exception:
        pass
    profiles = {}
    try:
        profiles = head.call({"op": "get_profile"})
    except Exception:
        pass
    set_stack(False)
    tracing.disable_tracing()
    tracing.clear_spans()
    # Tear the cluster down before the single-process device phase:
    # 16 idle workers still schedule heartbeats and samplers, which is
    # exactly the cross-arm jitter the paired windows try to cancel.
    ray_tpu.shutdown()

    dis_mean = statistics.median(dis_rates)
    dis_std = statistics.stdev(dis_rates)
    en_mean = statistics.median(en_rates)
    en_std = statistics.stdev(en_rates)
    overhead = 1.0 - statistics.median(ratios)
    print(f"{'multi_client_tasks_async[tracing only]':<50s} "
          f"{dis_mean:>12.1f} ± {dis_std:.1f} /s", flush=True)
    print(f"{'multi_client_tasks_async[harvest+sampler+watchdog]':<50s} "
          f"{en_mean:>12.1f} ± {en_std:.1f} /s", flush=True)

    # Device-telemetry phase (PR 19): marginal cost of the compile
    # hook + continuous roofline/MFU accounting on the engine step
    # path, same paired-window method.
    dev = device_phase()
    print(f"{'engine_steps[device telemetry off]':<50s} "
          f"{dev['off_steps_s']:>12.1f} ± {dev['off_std']:.1f} /s",
          flush=True)
    print(f"{'engine_steps[device telemetry on]':<50s} "
          f"{dev['on_steps_s']:>12.1f} ± {dev['on_std']:.1f} /s",
          flush=True)

    wd = (profiles or {}).get("watchdog", {})
    doc = {
        "probe": "profiling_watchdog_overhead",
        "scale": SCALE,
        "overhead_budget": OVERHEAD_BUDGET,
        "sampler_interval_s": SAMPLER_INTERVAL_S,
        "multi_client_tasks_async": {
            "disabled_ops_s": round(dis_mean, 1),
            "disabled_std": round(dis_std, 1),
            "enabled_ops_s": round(en_mean, 1),
            "enabled_std": round(en_std, 1),
            "overhead": round(overhead, 4),
        },
        "engine_device_telemetry": dev,
        "harvest_sweeps": sweeps[0],
        "harvested_spans": len((harvest or {}).get("spans", [])),
        "harvest_workers_polled": (harvest or {}).get(
            "workers_polled", 0),
        "profiled_workers": len((profiles or {}).get("workers", {})),
        "watchdog": {"enabled": wd.get("enabled", False),
                     "stragglers_flagged": wd.get(
                         "stragglers_flagged", 0)},
    }
    out_path = os.path.join(_ROOT, "PROF_BENCH.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print("PROF_BENCH_RESULTS " + json.dumps(doc), flush=True)
    rc = 0
    if overhead >= OVERHEAD_BUDGET:
        print(f"FAIL: harvest+sampler+watchdog overhead {overhead:.1%} "
              f">= {OVERHEAD_BUDGET:.0%} budget", file=sys.stderr)
        rc = 1
    else:
        print(f"ok: harvest+sampler+watchdog overhead {overhead:.1%} "
              f"({en_mean:.0f} vs {dis_mean:.0f} ops/s)", flush=True)
    if dev["overhead"] >= OVERHEAD_BUDGET:
        print(f"FAIL: device-telemetry overhead {dev['overhead']:.1%} "
              f">= {OVERHEAD_BUDGET:.0%} budget", file=sys.stderr)
        rc = 1
    else:
        print(f"ok: device-telemetry overhead {dev['overhead']:.1%} "
              f"({dev['on_steps_s']:.0f} vs {dev['off_steps_s']:.0f} "
              f"steps/s)", flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
