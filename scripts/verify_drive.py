"""End-to-end drive of the ray_tpu public API (library surface)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")
import numpy as np
import ray_tpu

rt = ray_tpu.init(num_cpus=4)
print("[1] init ok, cluster:", ray_tpu.cluster_resources())

@ray_tpu.remote
def add(a, b=0):
    return a + b

print("[2] task:", ray_tpu.get(add.remote(1, b=2)))

# nested + refs in containers
@ray_tpu.remote
def nested(d):
    return ray_tpu.get(d["ref"]) * 10

print("[3] nested w/ container ref:", ray_tpu.get(nested.remote({"ref": ray_tpu.put(7)})))

# large numpy through shm
arr = np.ones((2048, 1024), np.float32)
@ray_tpu.remote
def sum_(x):
    return float(x.sum())
print("[4] 8MB shm arg:", ray_tpu.get(sum_.remote(arr)))

# actors
@ray_tpu.remote(max_concurrency=2)
class Counter:
    def __init__(self, start):
        self.v = start
    def inc(self, n=1):
        self.v += n
        return self.v
    def crash(self):
        raise RuntimeError("actor method boom")

c = Counter.remote(100)
print("[5] actor calls:", ray_tpu.get([c.inc.remote(), c.inc.remote(5)]))
try:
    ray_tpu.get(c.crash.remote())
    print("[6] FAIL - no error raised")
except ray_tpu.TaskError as e:
    print("[6] actor method error propagates:", type(e).__name__)
print("[6b] actor alive after method error:", ray_tpu.get(c.inc.remote()))

# named actor
@ray_tpu.remote(name="registry", max_restarts=0)
class Registry:
    def who(self):
        return "registry-v1"
r = Registry.remote()
ray_tpu.get(r.who.remote())
h = ray_tpu.get_actor("registry")
print("[7] named actor lookup:", ray_tpu.get(h.who.remote()))

# kill
ray_tpu.kill(c)
time.sleep(0.5)
try:
    ray_tpu.get(c.inc.remote(), timeout=5)
    print("[8] FAIL - dead actor call returned")
except Exception as e:
    print("[8] dead actor call raises:", type(e).__name__)

# PROBES
try:
    add(1)  # direct call
except TypeError as e:
    print("[P1] direct call -> TypeError:", str(e)[:50])
try:
    ray_tpu.get("not a ref")
except TypeError as e:
    print("[P2] get(str) -> TypeError")
rt2 = ray_tpu.init(num_cpus=4)
print("[P3] double init returns same runtime:", rt2 is rt)
try:
    ray_tpu.get_actor("ghost")
except ValueError:
    print("[P4] get_actor(missing) -> ValueError")
@ray_tpu.remote(num_returns=2)
def wrong():
    return 1, 2, 3
try:
    ray_tpu.get(wrong.remote())
except ray_tpu.TaskError:
    print("[P5] wrong num_returns -> TaskError")

# async actors: awaits overlap (auto concurrency for coroutine methods).
class AsyncSleeper:
    async def nap(self, t):
        import asyncio
        await asyncio.sleep(t)
        return t

_s = ray_tpu.remote(AsyncSleeper).remote()
ray_tpu.get(_s.nap.remote(0.01))
_t0 = time.time()
assert ray_tpu.get([_s.nap.remote(0.3) for _ in range(8)]) == [0.3] * 8
assert time.time() - _t0 < 1.5, "async awaits did not overlap"
print("[P7] async actor overlapped 8x0.3s naps in %.2fs" % (time.time() - _t0))

# streaming generator tasks: items flow before the task finishes.
@ray_tpu.remote(num_returns="streaming")
def stream(n):
    for i in range(n):
        yield i * 10

got = [ray_tpu.get(r) for r in stream.remote(4)]
assert got == [0, 10, 20, 30], got
print("[P6] streaming generator ->", got)

t0 = time.time()
ray_tpu.shutdown()
print("[9] shutdown in %.2fs" % (time.time() - t0))
print("ALL OK")
