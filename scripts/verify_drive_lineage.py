"""Drive lineage reconstruction end-to-end through the public API:
lose the only shm copy of task results and watch gets transparently
re-execute the producing chain (reference ObjectRecoveryManager)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import numpy as np

import ray_tpu
from ray_tpu.core.exceptions import ObjectLostError
from ray_tpu.core.ids import ObjectID

MARK = f"/tmp/verify_lineage_{os.getpid()}"


def lose(rt, ref):
    oid = ObjectID.from_hex(ref.hex())
    rt.core.store.release(oid)
    rt.core.store.delete(oid)


def main():
    open(MARK, "w").close()
    rt = ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def base():
        with open(MARK, "a") as f:
            f.write("b")
        return np.arange(300_000, dtype=np.int64)

    @ray_tpu.remote
    def double(a):
        with open(MARK, "a") as f:
            f.write("d")
        return a * 2

    expected = np.arange(300_000, dtype=np.int64) * 2
    t0 = time.time()
    a = base.remote()
    b = double.remote(a)
    # .copy(): gets are zero-copy views into the arena; the raw view
    # would dangle once we deliberately delete the block below.
    out = ray_tpu.get(b).copy()
    assert (out == expected).all()
    print(f"[1] chain computed in {time.time() - t0:.2f}s, "
          f"runs={open(MARK).read()!r}")

    lose(rt, b)
    out2 = ray_tpu.get(b, timeout=30).copy()
    assert (out2 == expected).all()
    runs = open(MARK).read()
    assert sorted(runs) == ["b", "d", "d"], runs
    print(f"[2] leaf loss -> re-ran only its producer, runs={runs!r}")

    lose(rt, a)
    lose(rt, b)
    out3 = ray_tpu.get(b, timeout=30).copy()
    assert (out3 == expected).all()
    runs = open(MARK).read()
    assert sorted(runs) == ["b", "b", "d", "d", "d"], runs
    print(f"[3] chain loss -> recursive re-run, runs={runs!r}")

    p = ray_tpu.put(np.arange(300_000))
    lose(rt, p)
    try:
        ray_tpu.get(p, timeout=30)
        raise AssertionError("expected ObjectLostError")
    except ObjectLostError as e:
        print(f"[4] put() loss -> ObjectLostError: {str(e)[:60]}...")

    ray_tpu.shutdown()
    os.unlink(MARK)
    print("ALL OK")


if __name__ == "__main__":
    main()
