"""Time packed_prefill_admit at the bench wave shape."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import transformer as tfm
from ray_tpu.models.decoding import init_kv_pages, packed_prefill_admit


def main():
    config = tfm.TransformerConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=16, num_kv_heads=4,
        max_seq_len=2048, remat=False)
    c = config
    params = tfm.init_params(c, jax.random.key(0))
    params = jax.tree.map(
        lambda x: x.astype(c.dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x, params)
    P_total = tfm.num_params(c)
    rng = np.random.default_rng(0)
    ps, num_pages, max_batch = 128, 320, 128

    for (R, S_row, seg_len) in [(16, 1024, 128), (8, 2048, 128),
                                (16, 1024, 1024), (4, 1024, 128)]:
        nseg = R * S_row // seg_len
        segs_per_row = S_row // seg_len
        tokens = np.zeros((R, S_row), dtype=np.int32)
        positions = np.full((R, S_row), -1, dtype=np.int32)
        row_tables = np.zeros((R, S_row // ps), dtype=np.int32)
        seg_slot = np.full(nseg, max_batch, dtype=np.int32)
        seg_limit = np.zeros(nseg, dtype=np.int32)
        seg_eos = np.full(nseg, -1, dtype=np.int32)
        L = seg_len  # full segments
        pg = 0
        for i in range(min(nseg, max_batch)):
            r, si = divmod(i, segs_per_row)
            j0 = si * seg_len
            tokens[r, j0:j0 + L] = rng.integers(1, c.vocab_size, L)
            positions[r, j0:j0 + L] = np.arange(L)
            for k in range(seg_len // ps):
                row_tables[r, si * (seg_len // ps) + k] = \
                    pg % (num_pages - 2)
                pg += 1
            seg_slot[i] = i % max_batch
            seg_limit[i] = L + 128 - 1
        st = [jnp.zeros(max_batch, dtype=jnp.int32) for _ in range(5)]
        cache = init_kv_pages(c, num_pages, ps)
        state = {"cache": cache, "st": st}

        def run():
            first, state["cache"], *new_st = packed_prefill_admit(
                params, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(row_tables), jnp.asarray(seg_slot),
                jnp.asarray(seg_limit), jnp.asarray(seg_eos),
                state["cache"], *state["st"], c, seg_len)
            state["st"] = new_st
            return first

        jax.block_until_ready(run())
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(run())
            times.append(time.perf_counter() - t0)
        dt = min(times)
        ntok = R * S_row
        flops = 2 * P_total * ntok
        print(f"packed R={R:3d} S={S_row:5d} seg={seg_len:5d}: "
              f"{dt*1e3:8.1f} ms  {ntok/dt:9.0f} tok/s  "
              f"mfu={flops/dt/197e12:.3f}")
        del state
    return 0


if __name__ == "__main__":
    sys.exit(main())
