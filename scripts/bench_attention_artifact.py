"""Attention benchmark artifact (VERDICT r3 item 7).

Writes ONE JSON document to stdout with:
  - flash vs naive (dense XLA) attention on the REAL chip, fwd and
    fwd+bwd, at the headline train shape (b8 s2048) and the
    long-context shape (b2 s8192) — the naive path materializes the
    [s, s] score matrix in HBM, the Pallas flash kernel never does;
  - ring-attention step time over the 8-virtual-device CPU mesh
    (sequence-parallel ppermute ring; correctness is pinned by
    tests/test_ops_attention.py — the CPU wall time only demonstrates
    the sharded program executes end-to-end and scales by ring step,
    not kernel speed).

Run: python scripts/bench_attention_artifact.py > ATTN_BENCH_rNN.json
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_chained(step_fn, carry0, steps=50):
    """Time steps that CHAIN on device INSIDE one jitted fori_loop.

    On the tunneled dev chip a single dispatch costs ~50-100 ms, so a
    Python-level chain (one dispatch per step) swamps ms-scale kernels
    with dispatch latency — r4 under-reported flash fwd 4x this way.
    Running the whole chain as one device program and subtracting an
    empty-loop control of the same trip count isolates the kernel."""
    import jax
    from jax import lax

    @jax.jit
    def run(c):
        return lax.fori_loop(0, steps, lambda i, c: step_fn(c), c)

    @jax.jit
    def empty(c):
        return lax.fori_loop(
            0, steps,
            lambda i, c: jax.tree.map(lambda x: x * (1 + 1e-7), c), c)

    jax.block_until_ready(run(carry0))    # compile
    jax.block_until_ready(empty(carry0))
    tb = te = 1e9
    for _ in range(4):
        t0 = time.perf_counter()
        jax.block_until_ready(empty(carry0))
        te = min(te, time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.block_until_ready(run(carry0))
        tb = min(tb, time.perf_counter() - t0)
    return max((tb - te) / steps, 1e-9)


def chip_rows():
    import jax
    import jax.numpy as jnp

    from bench import _peak_flops
    from ray_tpu.ops.attention import attention_reference, flash_attention

    peak = _peak_flops(jax.devices()[0])
    rows = []
    for b, s, h, d in ((8, 2048, 14, 128), (2, 8192, 14, 128)):
        key = jax.random.key(0)
        q = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)
        k = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)
        v = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)
        causal_flops = 2 * b * h * s * s * d  # fwd, lower triangle x2 mms

        def fwd_step_of(f):
            # Chain the output back in as q: same shape/dtype, forces
            # sequential device execution with no host transfers.
            return jax.jit(lambda qq: f(qq, k, v))

        def bwd_step_of(f):
            loss = lambda q, k, v: f(q, k, v).astype(  # noqa: E731
                jnp.float32).sum()
            g = jax.grad(loss, argnums=(0, 1, 2))
            return jax.jit(lambda qq: g(qq, k, v)[0])  # dq chains as q

        flash = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, block_q=512, block_k=512)
        naive = lambda q, k, v: attention_reference(  # noqa: E731
            q, k, v, causal=True)

        naive_steps = 4 if s >= 4096 else 20  # dense s8192 is ~1.5 s/step
        row = {"shape": f"b{b} s{s} h{h} d{d}"}
        t = _time_chained(fwd_step_of(flash), q)
        row["flash_fwd_ms"] = round(t * 1e3, 2)
        row["flash_fwd_flops_frac"] = round(causal_flops / t / peak, 3)
        try:
            t = _time_chained(fwd_step_of(naive), q, steps=naive_steps)
            row["naive_fwd_ms"] = round(t * 1e3, 2)
            row["speedup_fwd"] = round(
                row["naive_fwd_ms"] / row["flash_fwd_ms"], 2)
        except Exception as e:  # noqa: BLE001 — dense s=8192 can OOM
            row["naive_fwd_ms"] = f"OOM: {type(e).__name__}"
        t = _time_chained(bwd_step_of(flash), q, steps=25)
        row["flash_fwd_bwd_ms"] = round(t * 1e3, 2)
        row["flash_fwd_bwd_flops_frac"] = round(
            3.5 * causal_flops / t / peak, 3)
        try:
            t = _time_chained(bwd_step_of(naive), q, steps=naive_steps)
            row["naive_fwd_bwd_ms"] = round(t * 1e3, 2)
            row["speedup_fwd_bwd"] = round(
                row["naive_fwd_bwd_ms"] / row["flash_fwd_bwd_ms"], 2)
        except Exception as e:  # noqa: BLE001
            row["naive_fwd_bwd_ms"] = f"OOM: {type(e).__name__}"
        rows.append(row)
    return rows


_RING_CHILD = r"""
import os, sys, time, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, %(root)r)
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.mesh import build_mesh

b, s, h, d = 2, 2048, 4, 64
key = jax.random.key(0)
q = jax.random.normal(key, (b, s, h, d), jnp.float32)
k = jax.random.normal(key, (b, s, h, d), jnp.float32)
v = jax.random.normal(key, (b, s, h, d), jnp.float32)
out = {}
for n_seq in (1, 2, 4, 8):
    mesh = build_mesh(axes={"seq": n_seq},
                      devices=jax.devices()[:n_seq])
    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                               causal=True))
    o = f(q, k, v); np.asarray(o)
    t0 = time.perf_counter()
    for _ in range(5):
        o = f(q, k, v)
    np.asarray(o)
    out[f"seq={n_seq}"] = round((time.perf_counter() - t0) / 5 * 1e3, 1)
print(json.dumps(out))
"""


def ring_rows():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["RAY_TPU_CHIPS"] = "none"
    try:
        res = subprocess.run(
            [sys.executable, "-c", _RING_CHILD % {"root": root}],
            capture_output=True, text=True, timeout=900, env=env)
    except subprocess.TimeoutExpired:
        # The chip measurements already collected must still be
        # emitted; a slow/loaded host only costs the ring section.
        return {"error": "ring child timed out (900s)"}
    if res.returncode != 0:
        return {"error": res.stderr[-500:]}
    return json.loads(res.stdout.strip().splitlines()[-1])


def main():
    import jax

    doc = {
        "metric": "attention_bench",
        "device": getattr(jax.devices()[0], "device_kind",
                          jax.devices()[0].platform),
        "chip": chip_rows(),
        "ring_attention_cpu_mesh_step_ms": ring_rows(),
        "note": ("flash = in-tree Pallas kernel (ops/attention.py), "
                 "naive = dense XLA reference materializing [s,s] "
                 "scores; timing = on-device fori_loop chain minus an "
                 "empty-loop control (r4 chained at Python level and "
                 "paid ~50-100 ms tunnel dispatch per step, "
                 "under-reporting flash fwd ~4x); ring rows time one "
                 "jitted step of sequence-parallel ring attention "
                 "(ops/ring_attention.py) on an n-device virtual CPU "
                 "mesh at fixed GLOBAL shape b2 s2048 h4 d64"),
    }
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
