"""Serve data-plane benchmark: the admission-controlled LLM engine under
four-digit client counts.

Run: python scripts/bench_serve.py  (writes one JSON line to stdout;
results committed as SERVE_BENCH.json).

Three phases, all through the same admission-controlled engine the serve
replicas run (serve/llm_engine.py):

  sustained_load   1k+ concurrent synthetic clients (each client = one
                   outstanding request awaiting its stream) against one
                   engine: TTFT/TPOT p50/p99 INCLUDING queueing delay,
                   aggregate tok/s and the bandwidth-roofline fraction
                   (bench_decode math: HBM_BW / (weight_bytes + avg live
                   KV bytes) x batch).  The whole-run fraction is the
                   headline — at 8x bench_decode's request count the
                   prefill/drain edge effects amortize, which is the
                   point of serving at scale.
  burst_shed       a burst of 4x the queue cap with a tight deadline:
                   admission raises QueueFull at the door, the deadline
                   sheds queued stragglers at the next step, and every
                   ADMITTED request still completes.  Reports the shed
                   rate and its queue_full/deadline split.
  prefill_interference
                   decode TPOT p99 for long-generation requests with a
                   continuous stream of prompt prefills arriving vs the
                   same decoders alone.  The per-step prefill token
                   budget (RAY_TPU_SERVE_PREFILL_BUDGET) is what keeps
                   the ratio near 1: admission work interleaves in
                   bounded chunks instead of stalling live slots for a
                   full wave.
  tracing_overhead paired tracing-on/off rows: the same workload with
                   and without a request-journey trace context on every
                   request (queue/prefill/decode phase spans recorded
                   into the in-process ring).  The tok/s delta is the
                   cost of the observability path; tests pin it small.
  disaggregated    (--disagg) paired mixed-vs-disaggregated rows: the
                   same interference workload with the prefill stream
                   on a separate engine (decode TPOT on the decode
                   engine's busy clock), plus a cross-replica
                   prefix-cache phase that hands KV bundles from a
                   prefill server to a decode server and reports the
                   decode side's prefix hit rate + token-exactness.

Honesty rules (bench_decode's): TPU shapes only run on a real TPU
(devices[0].platform == "tpu"); elsewhere the tiny-config CPU fallback
runs the same code paths and says so in the artifact.  TTFT is
add_request -> first token on the host; TPOT is (last - first)/(n-1)
per request; queueing time is NOT excluded from TTFT — a shed-free
queue under load is the admission scheduler's job, not the clock's.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs), q))


def _mk_engine(config, shape, **over):
    from ray_tpu.serve.llm_engine import LLMEngine

    kw = dict(page_size=shape["page_size"], num_pages=shape["num_pages"],
              max_batch=shape["max_batch"], multi_step=shape["multi_step"],
              max_queue=shape.get("max_queue", 4096),
              queue_timeout_s=0, prefill_budget=shape["prefill_budget"])
    kw.update(over)
    return LLMEngine(config, **kw)


def _warmup(eng, config, shape, rng):
    """Compile everything the measured loop hits: the packed admission
    wave, the decode chunk per context bucket, and the dirty-slot
    merge (mid-run admission while old slots finish)."""
    warm = [rng.integers(1, config.vocab_size,
                         shape["prompt_len"]).tolist()
            for _ in range(shape["max_batch"])]
    eng.generate(warm, max_new_tokens=shape["max_new"])
    eng.add_request(warm[0], max_new_tokens=shape["max_new"])
    eng.step()
    eng.add_request(warm[1], max_new_tokens=4)
    while eng.has_work():
        eng.step()


def _drive(eng, ids, t_add):
    """Step the engine to completion, timestamping first/last tokens."""
    results, t_first, t_done = {}, {}, {}
    steps = 0
    while eng.has_work():
        done = eng.step()
        now = time.perf_counter()
        steps += 1
        results.update(done)
        for rid in done:
            t_done[rid] = now
        for r in eng.slot_req:
            if r is not None and r.generated and r.req_id not in t_first:
                t_first[r.req_id] = now
        for rid in done:
            t_first.setdefault(rid, now)
    return results, t_first, t_done, steps


def run_sustained(config, shape, hbm_gb_s):
    from ray_tpu.models import transformer as tfm

    eng = _mk_engine(config, shape)
    rng = np.random.default_rng(0)
    _warmup(eng, config, shape, rng)

    n = shape["n_clients"]
    prompts = [rng.integers(1, config.vocab_size,
                            shape["prompt_len"]).tolist()
               for _ in range(n)]
    t0 = time.perf_counter()
    t_add, ids = {}, []
    for p in prompts:
        rid = eng.add_request(p, max_new_tokens=shape["max_new"])
        t_add[rid] = time.perf_counter()
        ids.append(rid)
    results, t_first, t_done, steps = _drive(eng, ids, t_add)
    dt = time.perf_counter() - t0
    assert set(ids) <= set(results), "missing results"
    gen_tokens = sum(len(results[i]) for i in ids)

    weight_bytes = 2 * tfm.num_params(config)
    kv_per_token = (2 * config.num_layers * config.num_kv_heads
                    * config.head_dim_ * 2)
    avg_ctx = shape["prompt_len"] + shape["max_new"] / 2
    kv_bytes = shape["max_batch"] * avg_ctx * kv_per_token
    roofline_tok_s = hbm_gb_s / (weight_bytes + kv_bytes) \
        * shape["max_batch"]
    tok_s = gen_tokens / dt
    frac = tok_s / roofline_tok_s
    # Full precision: on the tiny CPU shape the fraction is ~1e-5 and
    # round(_, 3) flattened it to 0.0 — a meaningless artifact row.
    print(f"sustained: {tok_s:.3e} tok/s vs roofline "
          f"{roofline_tok_s:.3e} tok/s (fraction {frac:.3e})",
          file=sys.stderr)
    ttft = [t_first[i] - t_add[i] for i in ids]
    tpot = [(t_done[i] - t_first[i]) / (len(results[i]) - 1)
            for i in ids if len(results[i]) > 1]
    return {
        "concurrent_clients": n,
        "tokens_per_sec": round(tok_s, 1),
        "roofline_tokens_per_sec": round(roofline_tok_s, 1),
        "roofline_fraction": frac,
        "roofline_fraction_pct": frac * 100.0,
        "ttft_p50_s": round(_pct(ttft, 50), 4),
        "ttft_p99_s": round(_pct(ttft, 99), 4),
        "tpot_p50_ms": round(_pct(tpot, 50) * 1e3, 3),
        "tpot_p99_ms": round(_pct(tpot, 99) * 1e3, 3),
        "generated_tokens": gen_tokens,
        "shed": eng.num_shed,
        "wall_s": round(dt, 2),
        "engine_steps": steps,
        "seq": f"{shape['prompt_len']}+{shape['max_new']}",
        "max_batch": shape["max_batch"],
    }


def run_burst_shed(config, shape):
    from ray_tpu.serve.llm_engine import QueueFull

    cap = 2 * shape["max_batch"]
    eng = _mk_engine(config, shape, max_queue=cap)
    rng = np.random.default_rng(1)
    _warmup(eng, config, shape, rng)

    burst = 4 * cap
    admitted, queue_full = [], 0
    deadline_s = shape["burst_deadline_s"]
    for _ in range(burst):
        p = rng.integers(1, config.vocab_size,
                         shape["prompt_len"]).tolist()
        try:
            admitted.append(eng.add_request(
                p, max_new_tokens=shape["max_new"],
                deadline_s=deadline_s))
        except QueueFull:
            queue_full += 1
    results, _, _, _ = _drive(eng, admitted, {})
    deadline_shed = sum(1 for i in admitted if i not in results)
    completed = sum(1 for i in admitted if i in results)
    shed = queue_full + deadline_shed
    return {
        "burst_clients": burst,
        "queue_cap": cap,
        "queue_full_rejects": queue_full,
        "deadline_sheds": deadline_shed,
        "completed": completed,
        "shed_rate": round(shed / burst, 3),
        "deadline_s": deadline_s,
    }


def run_prefill_interference(config, shape):
    """Decode TPOT p99 for long decoders, alone vs under a continuous
    prefill stream admitted within the per-step budget."""
    rng = np.random.default_rng(2)
    n_dec = max(2, shape["max_batch"] // 2)
    dec_prompts = [rng.integers(1, config.vocab_size,
                                shape["prompt_len"]).tolist()
                   for _ in range(n_dec)]

    def _measure(interfere):
        eng = _mk_engine(config, shape)
        # Full-shape warmup: the long generation walks context buckets
        # the short warmup never reaches, and the interference prompts
        # have their own prefill bucket — every compile must land here,
        # not in (only) the first measured run.
        eng.generate(dec_prompts,
                     max_new_tokens=shape["interf_max_new"])
        eng.generate([rng.integers(
            1, config.vocab_size,
            shape["interf_prompt_len"]).tolist()], max_new_tokens=1)
        _warmup(eng, config, shape, rng)
        ids = [eng.add_request(p,
                               max_new_tokens=shape["interf_max_new"])
               for p in dec_prompts]
        # Seat the decoders (first token out) before interference.
        t_first, t_done, results = {}, {}, {}
        while len(t_first) < len(ids) and eng.has_work():
            done = eng.step()
            now = time.perf_counter()
            results.update(done)
            for r in eng.slot_req:
                if r is not None and r.generated \
                        and r.req_id not in t_first:
                    t_first[r.req_id] = now
            for rid in done:
                t_first.setdefault(rid, now)
                t_done[rid] = now
        fill = []
        while eng.has_work() or (interfere and fill
                                 and any(i not in results for i in ids)):
            if interfere and len(eng.waiting) < 2 \
                    and any(i not in results for i in ids):
                # Keep a prefill backlog alive for the whole window.
                for _ in range(2):
                    fill.append(eng.add_request(
                        rng.integers(1, config.vocab_size,
                                     shape["interf_prompt_len"]).tolist(),
                        max_new_tokens=1))
            done = eng.step()
            now = time.perf_counter()
            results.update(done)
            for rid in done:
                t_done[rid] = now
            if all(i in results for i in ids):
                break
        tpot = [(t_done[i] - t_first[i]) / (len(results[i]) - 1)
                for i in ids if len(results.get(i, [])) > 1]
        return _pct(tpot, 99) * 1e3, len(fill)

    base_p99, _ = _measure(False)
    loaded_p99, n_fill = _measure(True)
    return {
        "decoders": n_dec,
        "decode_tpot_p99_ms_alone": round(base_p99, 3),
        "decode_tpot_p99_ms_with_prefill": round(loaded_p99, 3),
        "tpot_ratio": round(loaded_p99 / base_p99, 3),
        "prefill_requests_injected": n_fill,
        "prefill_budget": shape["prefill_budget"],
    }


def run_tracing_overhead(config, shape):
    """Paired tracing-on/off rows: the identical workload driven twice
    on fresh engines, once with a request-journey trace context on
    every request (phase spans recorded into the in-process ring) and
    once without.  Best-of-3 per arm to shave scheduler noise; the
    journey instrumentation is a handful of ring appends per request
    plus a sampled per-step snapshot, so the tok/s delta must stay
    small (the committed threshold is pinned by tests)."""
    from ray_tpu.util import tracing

    rng = np.random.default_rng(4)
    n = max(64, 4 * shape["max_batch"])
    prompts = [rng.integers(1, config.vocab_size,
                            shape["prompt_len"]).tolist()
               for _ in range(n)]

    def _arm(traced):
        eng = _mk_engine(config, shape)
        _warmup(eng, config, shape, rng)
        tracing.clear_spans()
        t0 = time.perf_counter()
        ids = []
        for i, p in enumerate(prompts):
            ctx = (f"{i:016x}", f"{i:016x}") if traced else None
            ids.append(eng.add_request(
                p, max_new_tokens=shape["max_new"], trace_ctx=ctx))
        results, _, _, _ = _drive(eng, ids, {})
        dt = time.perf_counter() - t0
        toks = sum(len(results[i]) for i in ids)
        spans = len(tracing.get_spans()) + tracing.dropped_span_count()
        tracing.clear_spans()
        return toks / dt, spans

    tps_on, tps_off, spans_on = 0.0, 0.0, 0
    for _ in range(3):  # alternate arms so drift hits both equally
        on, n_spans = _arm(True)
        off, _ = _arm(False)
        tps_on, tps_off = max(tps_on, on), max(tps_off, off)
        spans_on = max(spans_on, n_spans)
    overhead = (tps_off - tps_on) / tps_off * 100.0 if tps_off else 0.0
    print(f"tracing overhead: on={tps_on:.1f} off={tps_off:.1f} tok/s "
          f"({overhead:+.2f}%)", file=sys.stderr)
    return {
        "requests_per_arm": n,
        "tokens_per_sec_traced": round(tps_on, 1),
        "tokens_per_sec_untraced": round(tps_off, 1),
        "overhead_pct": round(overhead, 3),
        "spans_per_run": spans_on,
    }


def run_disaggregated(config, shape):
    """Paired mixed-vs-disaggregated rows for the prefill/decode split.

    Interference pair: the same long decoders + continuous prefill
    stream measured twice — MIXED (one engine runs both, prefill
    admission waves interleave with the decoders' steps) and
    DISAGGREGATED (the prefill stream runs on a separate engine, as a
    prefill-role replica would).  Decode TPOT is measured on the decode
    engine's BUSY clock (time inside its own step() calls), so the
    prefill engine's host time doesn't bleed into the disaggregated row
    — on a real deployment the pools are separate chips.

    Prefix pair: N requests sharing a system prompt flow
    prefill_only -> KV handoff -> decode_from across two LLMServer
    instances; the decode side's cross-replica prefix-cache hit rate
    and token-exactness vs a single mixed server are the row."""
    rng = np.random.default_rng(3)
    n_dec = max(2, shape["max_batch"] // 2)
    dec_prompts = [rng.integers(1, config.vocab_size,
                                shape["prompt_len"]).tolist()
                   for _ in range(n_dec)]

    def _measure(mode):
        eng_d = _mk_engine(config, shape)
        eng_p = eng_d if mode != "disaggregated" \
            else _mk_engine(config, shape)
        for eng in {id(eng_d): eng_d, id(eng_p): eng_p}.values():
            eng.generate(dec_prompts,
                         max_new_tokens=shape["interf_max_new"])
            eng.generate([rng.integers(
                1, config.vocab_size,
                shape["interf_prompt_len"]).tolist()], max_new_tokens=4)
            _warmup(eng, config, shape, rng)
        ids = [eng_d.add_request(p,
                                 max_new_tokens=shape["interf_max_new"])
               for p in dec_prompts]
        busy = 0.0  # decode engine's attributed clock
        t_first, t_done, results = {}, {}, {}
        fill = []
        while any(i not in results for i in ids):
            if mode != "alone" and len(eng_p.waiting) < 2:
                for _ in range(2):
                    fill.append(eng_p.add_request(
                        rng.integers(
                            1, config.vocab_size,
                            shape["interf_prompt_len"]).tolist(),
                        max_new_tokens=4))
            t0 = time.perf_counter()
            done = eng_d.step()
            busy += time.perf_counter() - t0
            results.update(done)
            for r in eng_d.slot_req:
                if r is not None and r.generated \
                        and r.req_id not in t_first:
                    t_first[r.req_id] = busy
            for rid in done:
                t_first.setdefault(rid, busy)
                t_done[rid] = busy
            if eng_p is not eng_d and eng_p.has_work():
                eng_p.step()  # prefill pool: not on the decode clock
        while eng_p.has_work():
            eng_p.step()  # drain stragglers (not measured)
        tpot = [(t_done[i] - t_first[i]) / (len(results[i]) - 1)
                for i in ids if len(results.get(i, [])) > 1]
        return _pct(tpot, 99) * 1e3, len(fill)

    alone_p99, _ = _measure("alone")
    rows = {}
    for mode in ("mixed", "disaggregated"):
        p99, n_fill = _measure(mode)
        rows[mode] = {
            "decode_tpot_p99_ms_alone": round(alone_p99, 3),
            "decode_tpot_p99_ms_with_prefill": round(p99, 3),
            "tpot_ratio": round(p99 / alone_p99, 3),
            "prefill_requests_injected": n_fill,
        }

    # -- cross-replica prefix pair -------------------------------------
    from ray_tpu.serve import llm as llm_mod

    LLMServer = llm_mod.LLMServer.func_or_class
    kw = dict(config=config, page_size=shape["page_size"],
              num_pages=shape["num_pages"], max_batch=shape["max_batch"],
              multi_step=shape["multi_step"],
              prefill_budget=shape["prefill_budget"])
    pre, dec, ref = LLMServer(**kw), LLMServer(**kw), LLMServer(**kw)
    sys_prompt = rng.integers(
        1, config.vocab_size, 2 * shape["page_size"]).tolist()
    n_req, max_new, matched = 6, 2 * shape["multi_step"], 0
    for _ in range(n_req):
        prompt = sys_prompt + rng.integers(
            1, config.vocab_size, 3).tolist()
        kv = pre.prefill_only(prompt, max_new_tokens=max_new)
        got = dec.decode_from(prompt, kv, max_new_tokens=max_new)
        want = ref._submit_and_wait([prompt], max_new, 0.0)[0]
        matched += int(got == want)
    hits = dec.engine.prefix_cache.hits
    rows["cross_replica_prefix"] = {
        "requests": n_req,
        "kv_handoffs": dec.engine.kv_imports,
        "handoff_fallbacks": dec.handoff_fallbacks,
        "prefix_hits": hits,
        "prefix_hit_rate": hits / n_req,
        "tokens_saved": dec.engine.prefix_cache.tokens_saved,
        "tokens_match_mixed_reference": matched == n_req,
    }
    print(f"disagg: tpot_ratio mixed={rows['mixed']['tpot_ratio']} "
          f"disaggregated={rows['disaggregated']['tpot_ratio']} "
          f"prefix_hit_rate={hits / n_req:.3f}", file=sys.stderr)
    return rows


def main():
    import jax

    from ray_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    hbm_gb_s = {"TPU v5 lite": 819e9, "TPU v5": 2765e9,
                "TPU v4": 1228e9}.get(
        getattr(devices[0], "device_kind", ""), 819e9)
    if on_tpu:
        # Same 1.0B GQA 4:1 model + page_size=128 the decode bench
        # measured best; 1024 clients = 8x DECODE_BENCH_r05's request
        # count, same per-request shape as its 128+128 headline row.
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=16, num_kv_heads=4,
            max_seq_len=2048, remat=False)
        shape = dict(n_clients=1024, prompt_len=128, max_new=128,
                     page_size=128, num_pages=320, max_batch=128,
                     multi_step=32, prefill_budget=4096,
                     interf_prompt_len=512, interf_max_new=256,
                     burst_deadline_s=1.0)
    else:
        config = tfm.TransformerConfig.tiny()
        shape = dict(n_clients=1024, prompt_len=8, max_new=8,
                     page_size=4, num_pages=64, max_batch=8,
                     multi_step=4, prefill_budget=16,
                     interf_prompt_len=16, interf_max_new=64,
                     burst_deadline_s=0.05)

    sustained = run_sustained(config, shape, hbm_gb_s)
    burst = run_burst_shed(config, shape)
    interference = run_prefill_interference(config, shape)
    tracing_overhead = run_tracing_overhead(config, shape)
    disagg = run_disaggregated(config, shape) \
        if "--disagg" in sys.argv[1:] else None
    print(json.dumps({
        "metric": "serve_tokens_per_sec",
        "value": sustained["tokens_per_sec"],
        "unit": "tokens/s",
        "concurrent_clients": sustained["concurrent_clients"],
        "roofline_fraction": sustained["roofline_fraction"],
        "roofline_note": ("whole-run rate (queueing + prefill + decode "
                          "+ drain) vs HBM_BW / (weight_bytes + avg "
                          "live KV bytes) x batch — bench_decode's "
                          "roofline, amortized over 8x its requests"),
        "sustained_load": sustained,
        "burst_shed": burst,
        "prefill_interference": interference,
        "tracing_overhead": tracing_overhead,
        **({"disaggregated": disagg} if disagg is not None else {}),
        "model_params": tfm.num_params(config),
        "device": getattr(devices[0], "device_kind", devices[0].platform),
        "on_tpu": on_tpu,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
