"""Push-broadcast scale probe: 1 GiB to N real node-manager processes.

Comparator row: the reference's release-test "broadcast 1 GiB to 50
nodes: 19.4 s" (BASELINE.md; ObjectManager Push path).  Here every node
manager is a REAL process with its own shm arena on ONE host — on the
probe host's single core the broadcast is memcpy/loopback-bound, so the
honest per-node number is GB/s of fan-out, reported next to the
measured host core count.

Writes/updates the broadcast row into SCALE_r04.json (merging with any
existing rows) and prints the row.

Run: python scripts/broadcast_probe.py [--nodes 8] [--gb 1.0]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--gb", type=float, default=1.0)
    ap.add_argument("--out", default="SCALE_r04.json")
    args = ap.parse_args(argv)

    import numpy as np

    import ray_tpu
    from ray_tpu.experimental import broadcast_object

    size = int(args.gb * (1 << 30))
    rt = ray_tpu.init(num_cpus=1, log_to_driver=False, _system_config={
        "object_store_memory": int(size * 1.5)})
    procs = []
    try:
        env = dict(os.environ)
        env["PYTHONUNBUFFERED"] = "1"
        for i in range(args.nodes):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.node_manager",
                 "--address", rt.address, "--node-id", f"bc-{i}",
                 "--num-cpus", "1", "--num-tpus", "0"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        want = {f"bc-{i}" for i in range(args.nodes)}
        deadline = time.time() + 120
        while time.time() < deadline:
            alive = {n["node_id"] for n in rt.state_list("nodes")
                     if n["alive"]}
            if want <= alive:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("node managers never registered")

        payload = np.empty(size, dtype=np.uint8)
        payload[:: 1 << 20] = 42
        ref = ray_tpu.put(payload)
        t0 = time.perf_counter()
        out = broadcast_object(ref)
        dt = time.perf_counter() - t0
        ok = sum(1 for v in out.values() if v == "ok")
        row = {
            "object_gb": args.gb,
            "nodes": args.nodes,
            "ok": ok,
            "wall_s": round(dt, 2),
            "aggregate_gb_per_s": round(args.gb * ok / dt, 2),
            "host_cpus": len(os.sched_getaffinity(0)),
            "reference_row": "1 GiB to 50 nodes in 19.4 s "
                             "(multi-host release test)",
            "note": ("N real node-manager processes with private shm "
                     "arenas on one host; single-core loopback/memcpy "
                     "bound — fan-out is concurrent per destination "
                     "with a 64 MB in-flight admission budget "
                     "(core/object_plane.py)"),
        }
        assert ok == args.nodes, out
        doc = {}
        out_path = os.path.join(REPO, args.out)
        if os.path.exists(out_path):
            with open(out_path) as f:
                doc = json.load(f)
        doc["broadcast"] = row
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps(row))
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
