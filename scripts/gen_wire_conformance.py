"""Generate the cross-language wire-conformance artifact.

VERDICT r5 item 8: the wire schema (core/wire_schema.py — the stack's
proto-IDL tier) needs a GOLDEN artifact a third-language client can be
validated against without running Python.  This script derives, from
the schema table alone:

  - the schema document itself (export_schema), and
  - a golden frame corpus: for every op, one maximal valid frame (all
    fields), one minimal valid frame (required fields only), and
    deterministic invalid mutants (missing required field, wrong field
    type, undeclared field, unknown op) with machine-readable reasons.

Frames are written in the JSON WIRE form the cross-language door
speaks (bytes as {"__bytes_b64__": ...} envelopes, core/rpc.py).  The
committed WIRE_CONFORMANCE.json is the contract: the in-tree test
(tests/test_wire_conformance.py) regenerates and diffs it (schema
drift fails CI until the corpus is regenerated), then replays every
frame through the same decode+validate path the ingress runs; a C++ /
Java / Go client generator replays the same file against its own
encoder.

Run: python scripts/gen_wire_conformance.py   (rewrites
WIRE_CONFORMANCE.json at the repo root)
"""

from __future__ import annotations

import base64
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.core.wire_schema import SCHEMA, export_schema  # noqa: E402

# Deterministic example value per declared field type, in JSON WIRE
# form (the form the JSON door transports; bytes ride b64 envelopes).
_EXAMPLES = {
    "str": "example",
    "int": 7,
    "float": 1.5,
    "bool": True,
    "bytes": {"__bytes_b64__": base64.b64encode(b"payload").decode()},
    "list": ["item"],
    "dict": {"k": "v"},
    "any": {"nested": ["any", 1]},
}

# A value guaranteed NOT to satisfy the declared type (for the
# wrong-type mutants).  "any" accepts everything -> no mutant.
_WRONG = {
    "str": 123, "int": "not-an-int", "float": "not-a-float",
    "bool": "not-a-bool", "bytes": 3.5, "list": "not-a-list",
    "dict": "not-a-dict",
}


def _example_for(spec: str):
    base = spec.rstrip("?").split("|")[0]
    return _EXAMPLES[base]


def _wrong_for(spec: str):
    tname = spec.rstrip("?")
    if tname == "any":
        return None
    # Union types ("bytes|str"): a float satisfies neither arm.
    if "|" in tname:
        return 3.5
    return _WRONG[tname]


def build_corpus() -> dict:
    golden = []
    for op in sorted(SCHEMA):
        fields = SCHEMA[op]
        maximal = {"op": op}
        minimal = {"op": op}
        for name, spec in sorted(fields.items()):
            maximal[name] = _example_for(spec)
            if not spec.endswith("?"):
                minimal[name] = _example_for(spec)
        golden.append({"op": op, "case": "maximal", "valid": True,
                       "frame": maximal})
        if minimal != maximal:
            golden.append({"op": op, "case": "minimal", "valid": True,
                           "frame": minimal})
        # invalid: first required field missing
        required = [n for n, t in sorted(fields.items())
                    if not t.endswith("?")]
        if required:
            broken = dict(minimal)
            broken.pop(required[0])
            golden.append({
                "op": op, "case": f"missing-{required[0]}",
                "valid": False,
                "reason": f"required field {required[0]!r} absent",
                "frame": broken})
        # invalid: first typable field wrong type
        for name, spec in sorted(fields.items()):
            wrong = _wrong_for(spec)
            if wrong is None:
                continue
            broken = dict(minimal)
            broken[name] = wrong
            golden.append({
                "op": op, "case": f"wrong-type-{name}", "valid": False,
                "reason": f"field {name!r} violates type {spec!r}",
                "frame": broken})
            break
        # invalid: undeclared field
        broken = dict(minimal)
        broken["__undeclared__"] = 1
        golden.append({
            "op": op, "case": "undeclared-field", "valid": False,
            "reason": "fields outside the contract are rejected",
            "frame": broken})
    golden.append({"op": "__unknown__", "case": "unknown-op",
                   "valid": False,
                   "reason": "unknown ops fail closed",
                   "frame": {"op": "__unknown__"}})
    return {
        "format": "ray_tpu wire conformance v1",
        "note": ("Golden corpus for non-Python clients (reference: the "
                 "proto IDL contract every language compiles against, "
                 "src/ray/protobuf/).  'frame' is the JSON WIRE form "
                 "(bytes as {'__bytes_b64__': ...}); a conforming "
                 "client encoder must produce frames the schema "
                 "accepts and must not produce any frame it rejects."),
        "schema": export_schema(),
        "golden": golden,
    }


def main() -> int:
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "WIRE_CONFORMANCE.json")
    doc = build_corpus()
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    n_valid = sum(1 for g in doc["golden"] if g["valid"])
    print(f"wrote {out}: {len(doc['schema']['ops'])} ops, "
          f"{len(doc['golden'])} frames ({n_valid} valid, "
          f"{len(doc['golden']) - n_valid} invalid)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
