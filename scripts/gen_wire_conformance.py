"""Regenerate the cross-language wire-conformance artifact.

Back-compat delegate: the corpus builder moved into the unified
static-analysis suite (ray_tpu/analysis/conformance_pass.py), which
also checks artifact freshness as the ``wire-corpus-drift`` lint rule.
This wrapper keeps the historical entry point and import surface
(tests/test_wire_conformance.py does ``from gen_wire_conformance
import build_corpus``).

Run: python scripts/gen_wire_conformance.py   (rewrites
WIRE_CONFORMANCE.json at the repo root), or equivalently
``python -m ray_tpu.analysis --regen-wire``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.analysis.conformance_pass import (  # noqa: E402,F401
    build_corpus,
    write_corpus,
)


def main() -> int:
    write_corpus()
    return 0


if __name__ == "__main__":
    sys.exit(main())
