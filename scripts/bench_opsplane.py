"""Ops-journal overhead probe: with RAY_TPU_OPS_JOURNAL_DIR set, every
flight-recorder event, harvested span row, and metrics snapshot also
spills to the durable journal (util/journal.py).  The spill must cost
< 5% on the control-plane hot path — append is an enqueue; JSON
serialization, batching, rotation, and fsync all live on the journal's
writer thread.

Same paired-window methodology as scripts/bench_profiling.py (the
`multi_client_tasks_async` shape, alternating A/B windows with order
reversal, per-round ratios, median): BOTH arms run the full always-on
ops plane — tracing, the 1 Hz per-worker resource sampler, a 0.5 Hz
cluster-wide harvest_spans sweep, the watchdog ticking head-side — so
the toggle isolates exactly the durable-journal spill (span rows on
every harvest, flight events as the scheduler works, metrics
snapshots) in the head/driver process.  Overhead is lost task
throughput, not microbenchmark arithmetic; a secondary
`per_event` section prices the raw enqueue itself (µs/event on the
flight-recorder record path, journal on vs off).

Writes OPSPLANE_BENCH.json at the repo root (tests/test_ops_journal
.py's budget test reads it) and exits nonzero if the paired measurement
shows >= 5% overhead.

Run: python scripts/bench_opsplane.py
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

OVERHEAD_BUDGET = 0.05
SAMPLER_INTERVAL_S = 1.0
WINDOW_S = 3.0
ROUNDS = 10


def _per_event_cost(jdir: str) -> dict:
    """Secondary stat: raw µs/event on flight_recorder.record, journal
    on vs off.  Bursts with drain gaps so the writer thread keeps up —
    this prices enqueue + GIL competition, not queue-full drops."""
    from ray_tpu.util import flight_recorder, journal

    def arm(on: bool) -> float:
        if on:
            os.environ["RAY_TPU_OPS_JOURNAL_DIR"] = jdir
        else:
            os.environ.pop("RAY_TPU_OPS_JOURNAL_DIR", None)
        journal.reset()
        n = 0
        t_rec = 0.0
        deadline = time.perf_counter() + 0.5
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            for i in range(200):
                flight_recorder.record("bench", "tick", seq=n + i,
                                       obj_bytes=4096)
            t_rec += time.perf_counter() - t0
            n += 200
            time.sleep(0.004)
        if on:
            journal.flush_all(timeout=10.0)
        journal.reset()
        return t_rec / n * 1e6

    off_us = arm(False)
    on_us = arm(True)
    os.environ.pop("RAY_TPU_OPS_JOURNAL_DIR", None)
    return {"off_us": round(off_us, 3), "on_us": round(on_us, 3),
            "added_us": round(on_us - off_us, 3)}


def main() -> int:
    import ray_tpu
    from ray_tpu.scripts.microbenchmark import SCALE
    from ray_tpu.util import journal, tracing

    jdir = tempfile.mkdtemp(prefix="opsplane-bench-")
    os.environ["RAY_TPU_OPS_JOURNAL_FSYNC_S"] = "0.05"
    os.environ["RAY_TPU_OPS_JOURNAL_MAX_BYTES"] = str(256 << 20)
    os.environ.pop("RAY_TPU_OPS_JOURNAL_DIR", None)

    rt = ray_tpu.init(num_cpus=16, log_to_driver=False)

    @ray_tpu.remote
    def small_task():
        return b"ok"

    ray_tpu.get([small_task.remote() for _ in range(16)])

    class TaskClient:
        def run_batch(self, n):
            import ray_tpu as rt_

            rt_.get([small_task.remote() for _ in range(n)])
            return n

    TC = ray_tpu.remote(TaskClient)
    tclients = [TC.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([c.run_batch.remote(1) for c in tclients])
    n = max(50, int(250 * SCALE))

    def multi_tasks():
        ray_tpu.get([c.run_batch.remote(n) for c in tclients])

    head = rt.core.client

    # Always-on ops plane in BOTH arms: sampler + harvest sweep +
    # watchdog + tracing.  The harvest sweep is what feeds the span
    # store — and therefore the "spans" journal stream — on the on arm.
    head.call({"op": "set_profile_config", "enabled": True,
               "interval_s": SAMPLER_INTERVAL_S})
    harvester_exit = threading.Event()

    def _harvester():
        while not harvester_exit.is_set():
            try:
                head.call({"op": "harvest_spans", "max_spans": 256,
                           "timeout_s": 10.0})
            # raylint: allow-swallow(best-effort background poller; bench tears it down)
            except Exception:
                pass
            harvester_exit.wait(2.0)

    threading.Thread(target=_harvester, name="bench-harvester",
                     daemon=True).start()

    def set_arm(on: bool) -> None:
        # The head runs in the driver process for an in-process
        # cluster, so toggling the env here gates the head-side spill
        # (span store, flight recorder, metrics) — the journaling
        # surface this bench prices.
        if on:
            os.environ["RAY_TPU_OPS_JOURNAL_DIR"] = jdir
        else:
            os.environ.pop("RAY_TPU_OPS_JOURNAL_DIR", None)
        journal.reset()

    def one_window(window_s: float = WINDOW_S) -> float:
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < window_s:
            multi_tasks()
            count += 1
        return count * 4 * n / (time.perf_counter() - start)

    assert not tracing.is_tracing_enabled()
    tracing.enable_tracing()
    multi_tasks()  # warmup

    off_rates, on_rates, ratios = [], [], []
    for r in range(ROUNDS):
        order = [(False, off_rates), (True, on_rates)]
        if r % 2:
            order.reverse()
        for on, rates in order:
            set_arm(on)
            time.sleep(0.3)  # settle: straddling sweeps/windows
            rates.append(one_window())
        ratios.append(on_rates[-1] / off_rates[-1])

    harvester_exit.set()
    set_arm(True)
    journal.flush_all(timeout=10.0)
    journaled = sum(len(journal.replay(jdir, s))
                    for s in ("flight", "spans", "metrics"))
    disk_bytes = sum(size
                     for s in ("flight", "spans", "metrics")
                     for _, _, _, size in journal.list_segments(jdir, s))
    dropped = 0
    for s in ("flight", "spans", "metrics"):
        j = journal.stream(s)
        if j is not None:
            dropped += j.stats()["dropped"]
    set_arm(False)
    tracing.disable_tracing()
    tracing.clear_spans()
    per_event = _per_event_cost(jdir)
    ray_tpu.shutdown()
    shutil.rmtree(jdir, ignore_errors=True)

    off_med = statistics.median(off_rates)
    on_med = statistics.median(on_rates)
    overhead = 1.0 - statistics.median(ratios)
    print(f"{'multi_client_tasks_async[journal off]':<45s} "
          f"{off_med:>12.1f} ± {statistics.stdev(off_rates):.1f} /s",
          flush=True)
    print(f"{'multi_client_tasks_async[journal on]':<45s} "
          f"{on_med:>12.1f} ± {statistics.stdev(on_rates):.1f} /s",
          flush=True)

    doc = {
        "probe": "ops_journal_overhead",
        "scale": SCALE,
        "overhead_budget": OVERHEAD_BUDGET,
        "journaling": {
            "off_ops_s": round(off_med, 1),
            "off_std": round(statistics.stdev(off_rates), 1),
            "on_ops_s": round(on_med, 1),
            "on_std": round(statistics.stdev(on_rates), 1),
            "overhead": round(overhead, 4),
            "records_journaled": journaled,
            "records_dropped": dropped,
            "disk_bytes": disk_bytes,
            "streams": ["flight", "spans", "metrics"],
        },
        "per_event": per_event,
    }
    out_path = os.path.join(_ROOT, "OPSPLANE_BENCH.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print("OPSPLANE_BENCH_RESULTS " + json.dumps(doc), flush=True)
    if overhead >= OVERHEAD_BUDGET:
        print(f"FAIL: ops-journal overhead {overhead:.1%} >= "
              f"{OVERHEAD_BUDGET:.0%} budget", file=sys.stderr)
        return 1
    print(f"ok: ops-journal overhead {overhead:.1%} "
          f"({on_med:.0f} vs {off_med:.0f} ops/s)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
