"""End-to-end drive of ray_tpu.train public entry points (verify skill)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import tempfile

import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

ray_tpu.init(num_cpus=4)
run_dir = tempfile.mkdtemp(prefix="vdt_")


def loop(config):
    import jax
    import jax.numpy as jnp

    ctx = train.get_context()
    shard = train.get_dataset_shard("train")
    w = jnp.zeros(())
    for i in range(3):
        w = jax.jit(lambda w: w + jnp.sum(jnp.asarray(shard)))(w)
        d = tempfile.mkdtemp()
        open(os.path.join(d, "w.txt"), "w").write(str(float(w)))
        train.report({"i": i, "w": float(w), "rank": ctx.get_world_rank()},
                     checkpoint=train.Checkpoint.from_directory(d))


res = JaxTrainer(
    loop,
    scaling_config=ScalingConfig(num_workers=2),
    run_config=RunConfig(storage_path=run_dir, name="drive"),
    datasets={"train": np.arange(8).astype(np.float32)},
    backend_config=train.JaxBackendConfig(
        distributed_init=True, platform="cpu", host_device_count=2),
).fit()
print("[1] fit result:", res.metrics)
assert res.metrics["i"] == 2 and res.metrics["rank"] == 0
assert res.checkpoint is not None
print("[2] checkpoint:", open(os.path.join(
    res.checkpoint.as_directory(), "w.txt")).read())
print("[3] history len:", len(res.metrics_history))
ray_tpu.shutdown()
print("ALL OK")
