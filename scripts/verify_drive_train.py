"""End-to-end drive of ray_tpu.train public entry points (verify skill)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax as _jax

# The dev sitecustomize re-points jax at the axon TPU tunnel; force CPU.
_jax.config.update("jax_platforms", "cpu")

import tempfile

import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

ray_tpu.init(num_cpus=4)
run_dir = tempfile.mkdtemp(prefix="vdt_")


def loop(config):
    import jax
    import jax.numpy as jnp

    ctx = train.get_context()
    shard = train.get_dataset_shard("train")
    w = jnp.zeros(())
    for i in range(3):
        w = jax.jit(lambda w: w + jnp.sum(jnp.asarray(shard)))(w)
        d = tempfile.mkdtemp()
        open(os.path.join(d, "w.txt"), "w").write(str(float(w)))
        train.report({"i": i, "w": float(w), "rank": ctx.get_world_rank()},
                     checkpoint=train.Checkpoint.from_directory(d))


res = JaxTrainer(
    loop,
    scaling_config=ScalingConfig(num_workers=2),
    run_config=RunConfig(storage_path=run_dir, name="drive"),
    datasets={"train": np.arange(8).astype(np.float32)},
    backend_config=train.JaxBackendConfig(
        distributed_init=True, platform="cpu", host_device_count=2),
).fit()
print("[1] fit result:", res.metrics)
assert res.metrics["i"] == 2 and res.metrics["rank"] == 0
assert res.checkpoint is not None
print("[2] checkpoint:", open(os.path.join(
    res.checkpoint.as_directory(), "w.txt")).read())
print("[3] history len:", len(res.metrics_history))

# [4] TorchTrainer: 2-worker gloo DDP with synchronized replicas.
from ray_tpu.train import ScalingConfig, TorchTrainer
from ray_tpu.train import session as train_session


def torch_loop(config):
    import torch
    import torch.distributed as dist
    import torch.nn as nn

    from ray_tpu.train.torch_backend import prepare_model

    torch.manual_seed(0)
    model = prepare_model(nn.Linear(2, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    rank = train_session.get_context().get_world_rank()
    g = torch.Generator().manual_seed(rank)
    X = torch.randn(32, 2, generator=g)
    y = X @ torch.tensor([[2.0], [-1.0]])
    for _ in range(40):
        opt.zero_grad()
        ((model(X) - y) ** 2).mean().backward()
        opt.step()
    w = (model.module if hasattr(model, "module") else model).weight
    gathered = [None, None]
    dist.all_gather_object(gathered, w.detach().numpy().tolist())
    train_session.report({"weights": gathered})


tres = TorchTrainer(
    torch_loop,
    scaling_config=ScalingConfig(num_workers=2,
                                 resources_per_worker={"CPU": 1})).fit()
w0, w1 = tres.metrics["weights"]
assert w0 == w1, (w0, w1)
print("[4] TorchTrainer DDP replicas in sync:", w0)

ray_tpu.shutdown()


def drive_async_checkpoint():
    """Async orbax checkpointing overlapping a live train loop."""
    import time

    import jax
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import load_pytree, save_pytree_async

    @jax.jit
    def step(w, x):
        g = jax.grad(lambda w: jnp.mean((x @ w - 1.0) ** 2))(w)
        return w - 0.1 * g

    w = jnp.zeros((256, 256))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 256)),
                    dtype=jnp.float32)
    w = step(w, x)  # compile
    d = tempfile.mkdtemp(prefix="vdt_ck_")
    save_pytree_async({"w": w}, d + "/warm").wait()  # orbax warmup
    t0 = time.perf_counter()
    h = save_pytree_async({"w": w, "meta": jnp.asarray(5)},
                          d + "/ck", step=5)
    submit = time.perf_counter() - t0
    for _ in range(20):  # train while the write flushes
        w = step(w, x)
    float(w[0, 0])
    path = h.wait()
    total = time.perf_counter() - t0
    back = load_pytree(path)
    assert int(back["meta"]) == 5 and back["w"].shape == (256, 256)
    print(f"[5] async ckpt: submit {submit*1e3:.0f}ms, 20 train steps "
          f"overlapped the {total*1e3:.0f}ms durable write; restore OK")


drive_async_checkpoint()
print("ALL OK")
