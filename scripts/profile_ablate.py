"""Ablation profiling: attribute decode/prefill step time to cache
writes, paged attention, and matmul body by stubbing pieces out.

Not part of the test suite — a diagnosis tool for the serving bench.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import transformer as tfm
import ray_tpu.models.decoding as dec
import ray_tpu.ops.paged_attention as pa


def timeit(fn, n=4):
    jax.block_until_ready(fn())
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    config = tfm.TransformerConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=16, num_kv_heads=4,
        max_seq_len=2048, remat=False)
    c = config
    params = tfm.init_params(c, jax.random.key(0))
    params = jax.tree.map(
        lambda x: x.astype(c.dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x, params)
    page_size, num_pages = 128, 320
    rng = np.random.default_rng(0)
    max_pages_per_seq = c.max_seq_len // page_size

    real_wtr = pa.write_token_rows
    real_pat = pa.paged_attention
    real_wpt = pa.write_page_tokens

    def fake_wtr(k_pages, v_pages, k_new, v_new, tables, positions):
        return k_pages, v_pages

    def fake_pat(q, k_pages, v_pages, tables, ctx, sm_scale=None):
        return q  # [B, H, D] passthrough

    def fake_wpt(k_pages, v_pages, k_new, v_new, tables, positions):
        return k_pages, v_pages

    # ---- decode32 ablations -------------------------------------------
    B, W = 128, 2
    toks = jnp.asarray(rng.integers(1, c.vocab_size, B), dtype=jnp.int32)
    pos = jnp.full((B,), 128, dtype=jnp.int32)
    ctx = jnp.full((B,), 129, dtype=jnp.int32)
    lim = jnp.full((B,), 100000, dtype=jnp.int32)
    eos = jnp.full((B,), -1, dtype=jnp.int32)
    tables = np.zeros((B, W), dtype=np.int32)
    for r in range(B):
        tables[r, 0] = (2 * r) % (num_pages - 2)
        tables[r, 1] = (2 * r + 1) % (num_pages - 2)
    tables = jnp.asarray(tables)

    variants = [
        ("full", {}),
        ("no_write", {"write_token_rows": fake_wtr}),
        ("no_attn", {"paged_attention": fake_pat}),
        ("no_both", {"write_token_rows": fake_wtr,
                     "paged_attention": fake_pat}),
    ]
    for name, patches in variants:
        for attr, fn in patches.items():
            setattr(pa, attr, fn)
        setattr(dec, "write_token_rows", patches.get(
            "write_token_rows", real_wtr))
        setattr(dec, "paged_attention", patches.get(
            "paged_attention", real_pat))
        cache = dec.init_kv_pages(c, num_pages, page_size)
        state = {"cache": cache, "toks": toks, "pos": pos, "ctx": ctx}
        fn_jit = jax.jit(
            lambda tk, ca, po, cx: dec.decode_multi_step.__wrapped__(
                params, tk, ca, tables, po, cx, lim, eos, c, 32),
            donate_argnums=(1,))

        def run():
            out, t2, p2, c2, state["cache"] = fn_jit(
                state["toks"], state["cache"], state["pos"], state["ctx"])
            state["cache"] = jax.tree.map(lambda x: x, state["cache"])
            return out

        # fresh cache each call since donated
        def run2():
            cache2 = dec.init_kv_pages(c, num_pages, page_size)
            out, *_ = fn_jit(toks, cache2, pos, ctx)
            return out

        dt = timeit(run2, n=3)
        print(f"decode32 {name:9s}: {dt*1e3:8.1f} ms "
              f"({dt/32*1e3:6.2f} ms/iter)", flush=True)
        for attr in patches:
            setattr(pa, attr, {"write_token_rows": real_wtr,
                               "paged_attention": real_pat}[attr])
        setattr(dec, "write_token_rows", real_wtr)
        setattr(dec, "paged_attention", real_pat)

    # ---- prefill ablations -------------------------------------------
    B, S = 128, 128
    tokens = jnp.asarray(
        rng.integers(1, c.vocab_size, (B, S)), dtype=jnp.int32)
    positions = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))
    ptables = np.zeros((B, max_pages_per_seq), dtype=np.int32)
    for r in range(B):
        ptables[r, 0] = (2 * r) % (num_pages - 2)
        ptables[r, 1] = (2 * r + 1) % (num_pages - 2)
    ptables = jnp.asarray(ptables)

    P = tfm.num_params(c)
    for name, patch in (("full", None), ("no_write", fake_wpt)):
        setattr(dec, "write_page_tokens", patch or real_wpt)
        fn_jit = jax.jit(
            lambda tk, po, ca, tb: dec.prefill.__wrapped__(
                params, tk, po, ca, tb, c), donate_argnums=(2,))

        def run3():
            cache2 = dec.init_kv_pages(c, num_pages, page_size)
            logits, _ = fn_jit(tokens, positions, cache2, ptables)
            return logits

        dt = timeit(run3, n=3)
        flops = 2 * P * B * S
        print(f"prefill  {name:9s}: {dt*1e3:8.1f} ms "
              f"mfu={flops/dt/197e12:.3f}", flush=True)
    setattr(dec, "write_page_tokens", real_wpt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
