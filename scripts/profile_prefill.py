"""Ad-hoc profiling of prefill/decode building blocks on the real chip.

Not part of the test suite; used to attribute serving wall time between
prefill compute, cache writes, and the decode gather widths.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import transformer as tfm
from ray_tpu.models.decoding import (
    decode_multi_step, init_kv_pages, prefill)


def timeit(fn, n=5):
    jax.block_until_ready(fn())  # warm
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(fn())
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    config = tfm.TransformerConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=16, num_kv_heads=4,
        max_seq_len=2048, remat=False)
    c = config
    params = tfm.init_params(c, jax.random.key(0))
    params = jax.tree.map(
        lambda x: x.astype(c.dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x, params)
    page_size, num_pages = 128, 320
    cache = init_kv_pages(c, num_pages, page_size)
    P = tfm.num_params(c)
    print(f"params {P/1e9:.2f}B, cache {cache['k'].nbytes*2/1e9:.2f} GB",
          file=sys.stderr)

    max_pages_per_seq = c.max_seq_len // page_size
    rng = np.random.default_rng(0)

    for B in (128, 64, 32, 16):
        S = 128
        tokens = jnp.asarray(
            rng.integers(1, c.vocab_size, (B, S)), dtype=jnp.int32)
        positions = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))
        # one page per row for the prompt
        tables = np.zeros((B, max_pages_per_seq), dtype=np.int32)
        for r in range(B):
            tables[r, 0] = (2 * r) % (num_pages - 2)
            tables[r, 1] = (2 * r + 1) % (num_pages - 2)
        tables = jnp.asarray(tables)

        state = {"cache": cache}

        def run():
            logits, state["cache"] = prefill(
                params, tokens, positions, state["cache"], tables, c)
            return logits

        dt = timeit(run, n=3)
        flops = 2 * P * B * S
        print(f"prefill B={B:4d} S={S}: {dt*1e3:8.1f} ms  "
              f"{B*S/dt:9.0f} tok/s  mfu={flops/dt/197e12:.3f}")
        cache = state["cache"]

    # decode chunk timing at two table widths
    B = 128
    toks = jnp.asarray(rng.integers(1, c.vocab_size, B), dtype=jnp.int32)
    pos = jnp.full((B,), 128, dtype=jnp.int32)
    ctx = jnp.full((B,), 129, dtype=jnp.int32)
    lim = jnp.full((B,), 100000, dtype=jnp.int32)
    eos = jnp.full((B,), -1, dtype=jnp.int32)
    for W in (2, 4, 16):
        tables = np.zeros((B, W), dtype=np.int32)
        for r in range(B):
            tables[r, 0] = (2 * r) % (num_pages - 2)
            tables[r, 1] = (2 * r + 1) % (num_pages - 2)
        tables = jnp.asarray(tables)
        state = {"cache": cache, "toks": toks, "pos": pos, "ctx": ctx}

        def run():
            out, t2, p2, c2, state["cache"] = decode_multi_step(
                params, state["toks"], state["cache"], tables,
                state["pos"], state["ctx"], lim, eos, c, 32)
            return out

        dt = timeit(run, n=3)
        per_iter = dt / 32
        traffic = 2 * P + B * 129 * (2 * c.num_layers * c.num_kv_heads
                                     * c.head_dim_ * 2)
        print(f"decode32 B={B} W={W:3d}: {dt*1e3:8.1f} ms "
              f"({per_iter*1e3:6.2f} ms/iter, roofline "
              f"{traffic/819e9*1e3:.2f} ms/iter, "
              f"frac={traffic/819e9/per_iter:.3f})")
        cache = state["cache"]
    return 0


if __name__ == "__main__":
    sys.exit(main())
