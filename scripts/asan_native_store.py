"""ASAN shard for the native shm arena (reference: bazel --config=asan
CI shards, .bazelrc:104-125).

Builds src/store/tpustore.cc with -fsanitize=address
(RAY_TPU_NATIVE_SANITIZE=address -> ray_tpu/native/build.py) and runs
tests/test_native_store.py + the multi-process fuzz in a subprocess with
libasan LD_PRELOADed (an ASan .so cannot be dlopen'ed into a vanilla
python otherwise).  Exits nonzero on any sanitizer report or test
failure.

Run: python scripts/asan_native_store.py
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    probe = subprocess.run(
        [os.environ.get("CC", "gcc"), "-print-file-name=libasan.so"],
        capture_output=True, text=True)
    libasan = probe.stdout.strip()
    if not libasan or not os.path.exists(libasan):
        print("libasan not found; skipping ASAN shard")
        return 0

    env = dict(os.environ)
    env["RAY_TPU_NATIVE_SANITIZE"] = "address"
    env["LD_PRELOAD"] = libasan
    # leak detection off: the long-lived python process 'leaks' plenty
    # of interpreter allocations by design; we're after heap/shm
    # overflows and use-after-free in the arena code.
    env["ASAN_OPTIONS"] = ("detect_leaks=0:abort_on_error=1:"
                           "handle_segv=1")
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_CHIPS"] = "none"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_native_store.py", "tests/test_native_store_fuzz.py",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env)
    if proc.returncode == 0:
        print("ASAN shard clean")
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
