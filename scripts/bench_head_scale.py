"""Head scale-out benchmark: paired before/after rows -> HEAD_BENCH.json.

ISSUE 13 measurement harness for the sharded-GCS / timer-wheel /
node-index / zero-copy work.  Three sections, each run twice in fresh
subprocesses of this script so the variants never share interpreter
state:

  - ``before``: current code with the new subsystems disabled via their
    knobs (RAY_TPU_GCS_SHARDS=0, RAY_TPU_NODE_INDEX=0,
    RAY_TPU_ZEROCOPY_MIN_BYTES=0, RAY_TPU_NM_PULL=0) — the legacy
    single-lock ingress, full node-table scans, and copying wire path.
  - ``after``: defaults (everything on).

Pairing both variants on the SAME host minutes apart is the same
methodology SCALE_r05 used for its control-vs-at-scale rows: absolute
rates move with host load/speed, the paired ratio isolates the code.
RPC_BENCH.json's recorded multi_client_tasks_async row is carried into
the output for reference, with ``host_factor`` = before/recorded so a
reader can see how the current host compares to the one that recorded
the baseline (the acceptance thresholds pinned in
tests/test_head_scale.py read this file).

Sections:
  multi_client_tasks_async  exact RPC_BENCH shape: 4 TaskClient actors
                            draining async no-op task batches.
  pg_create_ready           SPREAD placement groups (2 bundles x CPU:1)
                            created-to-ready on a 2,000-node simulated
                            cluster at 100/500/1,000 PGs.  The node
                            index makes this flat; the legacy scan is
                            O(nodes) per bundle.
  large_arg_submit          bytes memcpy'd through the wire encoder for
                            a 4 MiB task-arg payload (p50/p99 across
                            submits), measured from WIRE.bytes_sent
                            minus WIRE.zerocopy_bytes deltas.

Usage: python scripts/bench_head_scale.py            # full run
       python scripts/bench_head_scale.py --section pg --variant after
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "HEAD_BENCH.json")

# Knob values that turn the ISSUE-13 subsystems off (the "before" leg).
BEFORE_ENV = {
    "RAY_TPU_GCS_SHARDS": "0",
    "RAY_TPU_NODE_INDEX": "0",
    "RAY_TPU_ZEROCOPY_MIN_BYTES": "0",
    "RAY_TPU_NM_PULL": "0",
}

PG_NODES = int(os.environ.get("RAY_TPU_BENCH_PG_NODES", "2000"))
PG_COUNTS = (100, 500, 1000)
ARG_BYTES = 4 * 1024 * 1024


# ---------------------------------------------------------------------------
# sections (each runs inside its own subprocess; prints one JSON line)
# ---------------------------------------------------------------------------

def _section_multi_client() -> dict:
    import ray_tpu
    from ray_tpu.scripts.microbenchmark import SCALE, timeit

    rt = ray_tpu.init(num_cpus=16, log_to_driver=False)

    @ray_tpu.remote
    def small_task():
        return b"ok"

    ray_tpu.get([small_task.remote() for _ in range(16)])

    class TaskClient:
        def run_batch(self, n):
            import ray_tpu as rt_

            rt_.get([small_task.remote() for _ in range(n)])
            return n

    TC = ray_tpu.remote(TaskClient)
    tclients = [TC.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([c.run_batch.remote(1) for c in tclients])
    n = max(50, int(250 * SCALE))

    def burst():
        ray_tpu.get([c.run_batch.remote(n) for c in tclients])

    mean, std = timeit("multi_client_tasks_async", burst, multiplier=4 * n,
                       trials=3)
    ray_tpu.shutdown()
    return {"ops_per_s": round(mean, 1), "std": round(std, 1),
            "clients": 4, "batch": n}


def _section_pg() -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    cluster = Cluster(head_node_args={
        "num_cpus": 64, "log_to_driver": False,
        "_system_config": {"max_workers_per_node": 2}})
    t0 = time.perf_counter()
    for i in range(PG_NODES - 1):
        cluster.add_node(num_cpus=64, node_id=f"hb-{i}")
    reg_dt = time.perf_counter() - t0
    rows = []
    for count in PG_COUNTS:
        t0 = time.perf_counter()
        pgs = [placement_group([{"CPU": 1}] * 2, strategy="SPREAD")
               for _ in range(count)]
        ray_tpu.get([pg.ready() for pg in pgs], timeout=900)
        create_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        for pg in pgs:
            remove_placement_group(pg)
        remove_dt = time.perf_counter() - t0
        rows.append({"pgs": count,
                     "create_ready_per_s": round(count / create_dt, 1),
                     "remove_per_s": round(count / remove_dt, 1)})
        print(f"# pg {count}: {rows[-1]['create_ready_per_s']}/s create, "
              f"{rows[-1]['remove_per_s']}/s remove", file=sys.stderr,
              flush=True)
    cluster.shutdown()
    return {"nodes": PG_NODES,
            "register_per_s": round((PG_NODES - 1) / reg_dt, 1),
            "rows": rows}


def _section_large_arg() -> dict:
    # The wire leg of a multi-host large-arg submit: the serialized
    # spec (4 MiB ndarray arg) crossing one rpc hop.  bytes_copied is
    # what the encoder memcpy'd (header+payload concats and in-band
    # pickle bytes); the out-of-band path ships the arg buffer via
    # scatter-gather sendmsg instead.
    import numpy as np

    from ray_tpu.core import rpc

    def handler(conn, msg):
        return {"n": len(msg.get("args", ((),))[0][0])
                if msg.get("op") == "submit" else 0}

    srv = rpc.Server(host="127.0.0.1", port=0, handler=handler)
    cli = rpc.Client(srv.address)
    arg = np.random.default_rng(0).integers(
        0, 255, size=ARG_BYTES, dtype=np.uint8)
    payload = arg.tobytes()
    copied = []
    reps = 30
    for _ in range(reps):
        with rpc.WIRE.lock:
            sent0 = rpc.WIRE.bytes_sent
            zc0 = rpc.WIRE.zerocopy_bytes
        cli.call({"op": "submit", "args": ((payload,),)})
        with rpc.WIRE.lock:
            sent1 = rpc.WIRE.bytes_sent
            zc1 = rpc.WIRE.zerocopy_bytes
        copied.append((sent1 - sent0) - (zc1 - zc0))
    cli.close()
    srv.stop()
    copied.sort()
    return {"arg_bytes": ARG_BYTES, "reps": reps,
            "p50_bytes_copied": copied[reps // 2],
            "p99_bytes_copied": copied[min(reps - 1,
                                           int(reps * 0.99))]}


SECTIONS = {
    "multi_client": _section_multi_client,
    "pg": _section_pg,
    "large_arg": _section_large_arg,
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _run_variant(section: str, variant: str) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if variant == "before":
        env.update(BEFORE_ENV)
    cmd = [sys.executable, os.path.abspath(__file__),
           "--section", section, "--variant", variant]
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=1800)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(
            f"{section}/{variant} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}")
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    print(f"{section}/{variant}: {out} ({dt:.1f}s)", flush=True)
    return out


def _recorded_rpc_bench() -> float:
    path = os.path.join(os.path.dirname(OUT), "RPC_BENCH.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        return float(
            doc["results"]["multi_client_tasks_async"]["ops_s"])
    except Exception:
        return 0.0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", choices=sorted(SECTIONS), default="")
    ap.add_argument("--variant", choices=("before", "after"),
                    default="after")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args(argv)

    if args.section:
        # child mode: run one section under the caller-set knobs and
        # print its row as the last stdout line.
        print(json.dumps(SECTIONS[args.section]()), flush=True)
        return 0

    recorded = _recorded_rpc_bench()
    mc_before = _run_variant("multi_client", "before")
    mc_after = _run_variant("multi_client", "after")
    pg_before = _run_variant("pg", "before")
    pg_after = _run_variant("pg", "after")
    la_before = _run_variant("large_arg", "before")
    la_after = _run_variant("large_arg", "after")

    pg_rows = []
    before_rows = {r["pgs"]: r for r in pg_before["rows"]}
    for r in pg_after["rows"]:
        b = before_rows.get(r["pgs"], {})
        pg_rows.append({
            "pgs": r["pgs"],
            "before_per_s": b.get("create_ready_per_s", 0.0),
            "after_per_s": r["create_ready_per_s"],
            "before_remove_per_s": b.get("remove_per_s", 0.0),
            "after_remove_per_s": r["remove_per_s"],
        })

    before_ops = mc_before["ops_per_s"]
    doc = {
        "generated": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": len(os.sched_getaffinity(0)),
        "note": (
            "before = same build with RAY_TPU_GCS_SHARDS=0 "
            "RAY_TPU_NODE_INDEX=0 RAY_TPU_ZEROCOPY_MIN_BYTES=0 "
            "RAY_TPU_NM_PULL=0; after = defaults.  Both legs run "
            "back-to-back on this host (SCALE_r05 pairing "
            "methodology).  host_factor compares this host's paired "
            "'before' leg to the ops/s the RPC_BENCH row recorded on "
            "the host that produced it; absolute rates are not "
            "comparable across hosts."),
        "multi_client_tasks_async": {
            "recorded_rpc_bench_ops_per_s": recorded,
            "host_factor": round(before_ops / recorded, 3)
            if recorded else None,
            "before_ops_per_s": before_ops,
            "before_std": mc_before["std"],
            "after_ops_per_s": mc_after["ops_per_s"],
            "after_std": mc_after["std"],
            "clients": mc_after["clients"],
            "batch": mc_after["batch"],
        },
        "pg_create_ready": pg_rows,
        "pg_sim": {"nodes": pg_after["nodes"],
                   "register_per_s": pg_after["register_per_s"]},
        "large_arg_submit": {
            "arg_bytes": la_after["arg_bytes"],
            "p50_bytes_copied": la_after["p50_bytes_copied"],
            "p99_bytes_copied": la_after["p99_bytes_copied"],
            "before_p50_bytes_copied": la_before["p50_bytes_copied"],
            "before_p99_bytes_copied": la_before["p99_bytes_copied"],
        },
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
