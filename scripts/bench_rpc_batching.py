"""Control-plane micro-batching probe: the two microbenchmark rows the
coalesced-frame work targets, plus the wire counters that prove batching
is load-bearing (frames_sent vs msgs_sent on every driver link).

Replicates the `multi_client_tasks_async` and `single_client_wait_1k_refs`
shapes from ray_tpu/scripts/microbenchmark.py (same init, same burst
sizes, same timeit windows) so the numbers diff directly against the
recorded rounds (MICROBENCH_r05.json).  Emits one MICROBENCH-style JSON
document on stdout.

Run:          python scripts/bench_rpc_batching.py
A/B control:  RAY_TPU_RPC_NO_BATCH=1 python scripts/bench_rpc_batching.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R05 = {  # MICROBENCH_r05.json "results" rows this probe re-measures
    "multi_client_tasks_async": 3750.1,
    "single_client_wait_1k_refs": 3.8,
}


def _wire_stats(rt):
    clients = [rt.core.client] + list(rt.core._actor_conns.values())
    return {
        "frames_sent": sum(c.frames_sent for c in clients),
        "msgs_sent": sum(c.msgs_sent for c in clients),
        "batches_sent": sum(c.batches_sent for c in clients),
    }


def main() -> int:
    import ray_tpu
    from ray_tpu.scripts.microbenchmark import SCALE, timeit

    rt = ray_tpu.init(num_cpus=16, log_to_driver=False)
    rows = {}

    @ray_tpu.remote
    def small_task():
        return b"ok"

    ray_tpu.get([small_task.remote() for _ in range(16)])

    class TaskClient:
        def run_batch(self, n):
            import ray_tpu as rt_

            rt_.get([small_task.remote() for _ in range(n)])
            return n

    TC = ray_tpu.remote(TaskClient)
    tclients = [TC.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([c.run_batch.remote(1) for c in tclients])
    n = max(50, int(250 * SCALE))

    def multi_tasks():
        ray_tpu.get([c.run_batch.remote(n) for c in tclients])

    w0 = _wire_stats(rt)
    mean, std = timeit("multi_client_tasks_async", multi_tasks,
                       multiplier=4 * n, trials=2)
    w1 = _wire_stats(rt)
    rows["multi_client_tasks_async"] = {
        "ops_s": round(mean, 1), "std": round(std, 1),
        "r5_ops_s": R05["multi_client_tasks_async"],
        "vs_r5": round(mean / R05["multi_client_tasks_async"], 3),
        "driver_wire": {k: w1[k] - w0[k] for k in w0},
    }

    n_wait = max(200, int(1000 * SCALE))

    def wait_multiple_refs():
        not_ready = [small_task.remote() for _ in range(n_wait)]
        for _ in range(n_wait):
            _ready, not_ready = ray_tpu.wait(not_ready)

    w0 = _wire_stats(rt)
    mean, std = timeit("single_client_wait_1k_refs", wait_multiple_refs,
                       trials=2, window_s=0.5)
    w1 = _wire_stats(rt)
    rows["single_client_wait_1k_refs"] = {
        "ops_s": round(mean, 1), "std": round(std, 1),
        "r5_ops_s": R05["single_client_wait_1k_refs"],
        "vs_r5": round(mean / R05["single_client_wait_1k_refs"], 3),
        "driver_wire": {k: w1[k] - w0[k] for k in w0},
    }

    from ray_tpu.core import rpc

    doc = {
        "probe": "rpc_batching",
        "batching_enabled": rpc.batching_enabled(),
        "scale": SCALE,
        "results": rows,
    }
    print("RPC_BATCHING_RESULTS " + json.dumps(doc), flush=True)
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
