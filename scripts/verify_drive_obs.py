"""Observability drive: tracing propagation, /metrics wire counters,
/api/trace + /api/flight_recorder, task-event-fed state API.

Run: timeout 180 python scripts/verify_drive_obs.py
"""
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import ray_tpu
from ray_tpu.util import tracing

rt = ray_tpu.init(num_cpus=4, log_to_driver=False)
tracing.enable_tracing()


@ray_tpu.remote
def leaf(x):
    return x + 1


@ray_tpu.remote
def branch(x):
    return ray_tpu.get(leaf.remote(x)) + 10


# Only 2 concurrent blocking parents on 4 CPUs: a parent blocked in
# get() holds its worker, so leaves need free slots to run on.
out = [ray_tpu.get([branch.remote(i), branch.remote(i + 1)])
       for i in range(0, 6, 2)]
print("[1] nested results ok:", out[0] == [11, 12] and out[2] == [15, 16],
      flush=True)
ray_tpu.get([leaf.remote(i) for i in range(40)])
print("[1b] 40 flat leaf tasks done", flush=True)

from ray_tpu.state import api as state_api

deadline = time.time() + 15
traced = []
while time.time() < deadline:
    rows = state_api.list_tasks()
    traced = [r for r in rows if r.get("trace_id") and r.get("span_id")]
    if len(traced) >= 40:
        break
    time.sleep(0.3)
tids = {r["trace_id"] for r in traced}
print(f"[2] {len(traced)} traced task rows, {len(tids)} trace ids",
      flush=True)
assert len(traced) >= 40, traced[:3]
by_name = {}
for r in traced:
    by_name.setdefault(r["name"], []).append(r)
br = by_name["branch"][0]
parents = {b["span_id"] for b in by_name["branch"]}
leaf_rows = by_name["leaf"]
assert any(l["parent_span_id"] in parents for l in leaf_rows), \
    (leaf_rows[0], sorted(parents)[:2])
assert {l["trace_id"] for l in leaf_rows} & \
    {b["trace_id"] for b in by_name["branch"]}
print("[3] leaf parents to branch execution span; shared trace id",
      flush=True)

one = state_api.get_task(br["task_id"])
assert one and one["span_id"] == br["span_id"]
print("[4] get_task returns the traced row", flush=True)

from ray_tpu.dashboard import Dashboard

dash = Dashboard(rt)
url = dash.url


def fetch(path):
    with urllib.request.urlopen(url + path, timeout=15) as f:
        return f.read().decode()


metrics = fetch("/metrics")
for needle in ("rpc_frames_total", 'direction="sent"', "rpc_batch_size_count",
               "rpc_frames_by_kind_total", "ray_tpu_lease_grants_total"):
    assert needle in metrics, needle
sent = [ln for ln in metrics.splitlines()
        if ln.startswith("rpc_frames_total") and 'direction="sent"' in ln]
assert sent and float(sent[0].rsplit(" ", 1)[1]) > 0, sent
print("[5] /metrics exports nonzero wire counters + scheduler counters",
      flush=True)

trace = json.loads(fetch("/api/trace"))
cats = {e.get("cat") for e in trace}
assert "span" in cats, cats
spans = [e for e in trace if e.get("cat") == "span"]
print(f"[6] /api/trace: {len(trace)} events, {len(spans)} span slices, "
      f"cats={sorted(c for c in cats if c)}", flush=True)

fr = json.loads(fetch("/api/flight_recorder"))
assert fr["stats"]["capacity"] >= 16 and isinstance(fr["events"], list)
print(f"[7] /api/flight_recorder: {len(fr['events'])} events, "
      f"stats={fr['stats']}", flush=True)

out = "/tmp/_obs_trace.json"
n = tracing.export_chrome_trace(out)
doc = json.load(open(out))
assert isinstance(doc, list) and len(doc) == n and n > 0
os.remove(out)
print(f"[8] export_chrome_trace wrote {n} events", flush=True)

dash.stop()
ray_tpu.shutdown()
print("OBS DRIVE ALL OK", flush=True)
