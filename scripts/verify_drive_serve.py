"""Verify driver: serve library end-to-end through the real runtime.

Covers: deployment + run, handle calls, composition, scaling redeploy,
HTTP proxy round trip, status, delete, shutdown.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import ray_tpu  # noqa: E402
from ray_tpu import serve  # noqa: E402


def main():
    ray_tpu.init(num_cpus=8)
    t0 = time.time()

    # [1] basic deployment + handle
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return {"echo": x, "replica":
                    serve.get_replica_context().replica_id}

    h = serve.run(Echo.bind(), name="echo", route_prefix=None)
    out = h.remote("hi").result()
    assert out["echo"] == "hi"
    print(f"[1] deploy+call ok in {time.time()-t0:.1f}s: {out['replica']}")

    # [2] spread across replicas
    seen = {h.remote(i).result()["replica"] for i in range(20)}
    print(f"[2] replicas hit: {sorted(seen)}")
    assert len(seen) == 2

    # [3] composition
    @serve.deployment
    def plus_one(x):
        return x + 1

    @serve.deployment
    class Chain:
        def __init__(self, inner):
            self.inner = inner

        def __call__(self, x):
            return self.inner.remote(x).result() * 10

    ch = serve.run(Chain.bind(plus_one.bind()), name="chain",
                   route_prefix=None)
    assert ch.remote(4).result() == 50
    print("[3] composition ok")

    # [4] HTTP proxy
    serve.start(proxy=True)

    @serve.deployment
    class Web:
        def __call__(self, req: serve.Request):
            return {"sum": sum((req.json() or {}).get("xs", []))}

    serve.run(Web.bind(), name="web", route_prefix="/web")
    addr = serve.proxy_address()
    r = urllib.request.Request(
        addr + "/web", data=json.dumps({"xs": [1, 2, 3]}).encode())
    deadline = time.time() + 15
    while True:
        try:
            with urllib.request.urlopen(r, timeout=10) as resp:
                assert json.loads(resp.read()) == {"sum": 6}
            break
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.3)
    print(f"[4] http proxy ok at {addr}")

    # [5] status + delete
    st = serve.status()
    assert st["echo"].status == "RUNNING", st
    serve.delete("chain")
    assert "chain" not in serve.status()
    print(f"[5] status/delete ok; apps: {sorted(serve.status())}")

    # [6] LLM deployment: continuous-batching paged-attention engine.
    import jax.numpy as jnp

    from ray_tpu.serve.llm import LLMServer

    llm = serve.run(
        LLMServer.bind(config_kwargs=dict(
            num_layers=2, num_heads=4, num_kv_heads=2, hidden_size=32,
            intermediate_size=64, vocab_size=64, max_seq_len=64,
            dtype=jnp.float32, use_flash=False)),
        name="llm", route_prefix=None)
    before = llm.stats.remote().result()
    outs = llm.generate_batch.remote(
        [[1, 2, 3], [4, 5, 6, 7]], max_new_tokens=4).result()
    assert len(outs) == 2 and all(len(o) == 4 for o in outs), outs
    stats = llm.stats.remote().result()
    # Page accounting returns to the idle level (num_pages - 1: the last
    # physical page is the decode scratch and is never allocatable).
    assert stats["free_pages"] == before["free_pages"], (before, stats)
    assert stats["free_pages"] == stats["num_pages"] - 1, stats
    print(f"[6] LLM paged-attention deployment ok ({outs})")

    serve.shutdown()
    ray_tpu.shutdown()
    print("SERVE DRIVE OK")


if __name__ == "__main__":
    main()
