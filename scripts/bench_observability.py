"""Observability overhead probe: the flight-recorder / tracing /
wire-metrics stack must cost < 5% on the control-plane hot path.

Re-measures the `multi_client_tasks_async` shape from
scripts/bench_rpc_batching.py (same init, same burst sizes, same timeit
windows — numbers diff directly against RPC_BENCH.json) twice in one
process: once with tracing disabled (the shipped default: wire counters
and the flight recorder are still live, both always-on) and once with
tracing enabled, which turns on span recording on every driver submit,
trace_ctx propagation on every TaskSpec, and forced execution-span
recording in every worker.

Writes OBS_BENCH.json at the repo root (tests/test_observability.py's
overhead smoke test reads it) and exits nonzero if the paired
measurement shows >= 5% overhead.

Run: python scripts/bench_observability.py
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# RPC_BENCH.json multi_client_tasks_async — the PR 1 recorded baseline
# this machine's "disabled" row should roughly reproduce.
RPC_BENCH_OPS_S = 4952.3

OVERHEAD_BUDGET = 0.05


def main() -> int:
    import ray_tpu
    from ray_tpu.scripts.microbenchmark import SCALE
    from ray_tpu.util import tracing

    ray_tpu.init(num_cpus=16, log_to_driver=False)

    @ray_tpu.remote
    def small_task():
        return b"ok"

    ray_tpu.get([small_task.remote() for _ in range(16)])

    class TaskClient:
        def run_batch(self, n):
            import ray_tpu as rt_

            rt_.get([small_task.remote() for _ in range(n)])
            return n

    TC = ray_tpu.remote(TaskClient)
    tclients = [TC.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([c.run_batch.remote(1) for c in tclients])
    n = max(50, int(250 * SCALE))

    def multi_tasks():
        ray_tpu.get([c.run_batch.remote(n) for c in tclients])

    # Interleave off/on windows (A/B/A/B...) instead of two sequential
    # timeit phases: cluster throughput drifts a few percent over the
    # run, and pairing windows cancels that drift out of the overhead
    # figure.  Same 0.7s windows and ops/s math as microbenchmark.timeit.
    import statistics
    import time as _time

    def one_window(window_s: float = 2.0) -> float:
        start = _time.perf_counter()
        count = 0
        while _time.perf_counter() - start < window_s:
            multi_tasks()
            count += 1
        return count * 4 * n / (_time.perf_counter() - start)

    assert not tracing.is_tracing_enabled()
    multi_tasks()  # warmup
    dis_rates, en_rates, ratios = [], [], []
    for r in range(8):
        # Alternate which mode goes first: throughput decays slowly as
        # the head's task table grows, so a fixed order would bill that
        # decay entirely to whichever mode always ran second.
        order = [(False, dis_rates), (True, en_rates)]
        if r % 2:
            order.reverse()
        for on, rates in order:
            (tracing.enable_tracing if on
             else tracing.disable_tracing)()
            rates.append(one_window())
        # Overhead comes from per-round ratios, not the two medians:
        # adjacent windows share the machine's load conditions, so the
        # ratio cancels drift that dwarfs the effect being measured.
        ratios.append(en_rates[-1] / dis_rates[-1])
    spans = len(tracing.get_spans())
    dropped = tracing.dropped_span_count()
    tracing.disable_tracing()
    tracing.clear_spans()

    dis_mean = statistics.median(dis_rates)
    dis_std = statistics.stdev(dis_rates)
    en_mean = statistics.median(en_rates)
    en_std = statistics.stdev(en_rates)
    overhead = 1.0 - statistics.median(ratios)
    print(f"{'multi_client_tasks_async[tracing off]':<50s} "
          f"{dis_mean:>12.1f} ± {dis_std:.1f} /s", flush=True)
    print(f"{'multi_client_tasks_async[tracing on]':<50s} "
          f"{en_mean:>12.1f} ± {en_std:.1f} /s", flush=True)

    from ray_tpu.core import rpc
    from ray_tpu.util import flight_recorder
    doc = {
        "probe": "observability_overhead",
        "scale": SCALE,
        "overhead_budget": OVERHEAD_BUDGET,
        "multi_client_tasks_async": {
            "disabled_ops_s": round(dis_mean, 1),
            "disabled_std": round(dis_std, 1),
            "enabled_ops_s": round(en_mean, 1),
            "enabled_std": round(en_std, 1),
            "overhead": round(overhead, 4),
            "rpc_bench_ops_s": RPC_BENCH_OPS_S,
            "disabled_vs_rpc_bench": round(dis_mean / RPC_BENCH_OPS_S, 3),
        },
        "driver_spans_recorded": spans,
        "driver_spans_dropped": dropped,
        "flight_recorder": flight_recorder.stats(),
        "wire": {s["name"]: {str(k): v for k, v in s["series"].items()}
                 for s in rpc.wire_metric_snapshots()
                 if s["kind"] == "counter"},
    }
    out_path = os.path.join(_ROOT, "OBS_BENCH.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print("OBS_BENCH_RESULTS " + json.dumps(doc), flush=True)
    ray_tpu.shutdown()
    if overhead >= OVERHEAD_BUDGET:
        print(f"FAIL: tracing overhead {overhead:.1%} >= "
              f"{OVERHEAD_BUDGET:.0%} budget", file=sys.stderr)
        return 1
    print(f"ok: tracing overhead {overhead:.1%} "
          f"({en_mean:.0f} vs {dis_mean:.0f} ops/s)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
