"""End-to-end drive of the ray_tpu.tune public surface."""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax

jax.config.update("jax_platforms", "cpu")

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig

ray_tpu.init(num_cpus=8)
base = tempfile.mkdtemp()


def objective(config):
    for step in range(3):
        tune.report({"score": -abs(config["x"] - 2.0) - 0.01 * step})


grid = tune.Tuner(
    objective,
    param_space={"x": tune.grid_search([0.0, 2.0, 5.0]),
                 "noise": tune.uniform(0, 1e-6)},
    tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=1),
    run_config=RunConfig(storage_path=base, name="drive"),
).fit()
best = grid.get_best_result()
assert abs(best.metrics["score"] + 0.02) < 1e-3, best.metrics
print("[1] grid search found x=2.0, score:", best.metrics["score"])

state = json.load(open(os.path.join(base, "drive", "experiment_state.json")))
assert all(t["state"] == "TERMINATED" for t in state["trials"])
print("[2] experiment state persisted:", len(state["trials"]), "trials")


def ckpt_fn(config):
    ck = tune.get_checkpoint()
    start = json.load(open(os.path.join(
        ck.as_directory(), "s.json")))["i"] if ck else 0
    for i in range(start, 3):
        d = tempfile.mkdtemp()
        json.dump({"i": i + 1}, open(os.path.join(d, "s.json"), "w"))
        tune.report({"i": i}, checkpoint=Checkpoint.from_directory(d))


grid = tune.Tuner(
    ckpt_fn, param_space={},
    tune_config=tune.TuneConfig(metric="i", mode="max"),
    run_config=RunConfig(storage_path=base, name="ck"),
).fit()
r = grid.get_best_result()
assert r.checkpoint is not None
print("[3] checkpointed trial, final i:", r.metrics["i"])


def asha_fn(config):
    for step in range(1, 16):
        tune.report({"s": config["q"] * step})


grid = tune.Tuner(
    asha_fn,
    param_space={"q": tune.grid_search([0.1, 1.0, 4.0, 16.0])},
    tune_config=tune.TuneConfig(
        metric="s", mode="max",
        scheduler=tune.AsyncHyperBandScheduler(
            grace_period=2, reduction_factor=3, max_t=15)),
    run_config=RunConfig(storage_path=base, name="asha"),
).fit()
iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
assert iters[0] < 15 and iters[-1] == 15, iters
print("[4] ASHA early-stopped weak trials:", iters)

# [5] TPE adaptive search finds the bowl minimum.
from ray_tpu.tune import TPESearcher


def bowl(config):
    tune.report({"loss": (config["x"] - 0.3) ** 2
                 + (config["y"] + 0.2) ** 2})


tpe_res = tune.Tuner(
    bowl,
    param_space={"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)},
    tune_config=tune.TuneConfig(
        metric="loss", mode="min", num_samples=24,
        search_alg=TPESearcher(n_initial=8, seed=0),
        max_concurrent_trials=2),
).fit()
best = tpe_res.get_best_result(metric="loss", mode="min").metrics["loss"]
assert best < 0.1, best
print(f"[5] TPE best loss: {best:.4f}")

ray_tpu.shutdown()
print("TUNE DRIVE OK")
