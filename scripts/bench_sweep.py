"""MFU sweep over remat policy x batch on the real chip.

Usage: python scripts/bench_sweep.py [policy batch [seq]] ...
  with no args runs the default grid for the 0.9B headline config.
Prints one line per combo; OOMs are reported and skipped.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(policy: str, batch: int, seq: int = 2048, steps: int = 10):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as tfm
    from ray_tpu.parallel.mesh import build_mesh
    from ray_tpu.train.train_state import ShardedTrainStep, default_optimizer

    from bench import _peak_flops

    # Policy suffixes: "+nu16" stores Adam's second moment in bf16
    # (train/optim.py); "+fce" uses the fused chunked cross-entropy
    # (ops/fused_ce.py) — both buy the HBM headroom that lets faster
    # remat policies fit.
    nu16 = "+nu16" in policy
    fce = "+fce" in policy
    policy = policy.replace("+nu16", "").replace("+fce", "")
    config = tfm.TransformerConfig(
        vocab_size=32000, hidden_size=1792, intermediate_size=7168,
        num_layers=16, num_heads=14, num_kv_heads=14, max_seq_len=seq,
        remat_policy=policy, fused_ce=fce,
    )
    devices = jax.devices()
    mesh = build_mesh(axes={"fsdp": len(devices)}, devices=devices)
    ts = ShardedTrainStep(
        config, mesh,
        optimizer=default_optimizer(
            warmup_steps=10, total_steps=1000, mu_dtype=jnp.bfloat16,
            nu_dtype=jnp.bfloat16 if nu16 else None))
    state = ts.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch_np = {"tokens": jnp.asarray(
        rng.integers(0, config.vocab_size, (batch, seq + 1)),
        dtype=jnp.int32)}
    state, metrics = ts.step(state, batch_np)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ts.step(state, batch_np)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    tok = batch * seq * steps / dt
    mfu = tok * tfm.flops_per_token(config, seq) / (
        _peak_flops(devices[0]) * len(devices))
    print(f"policy={policy:<10s} b={batch} seq={seq}: "
          f"MFU={mfu:.4f} tok/s={tok:.0f}", flush=True)
    return mfu


def main():
    args = sys.argv[1:]
    if args:
        combos = []
        i = 0
        while i < len(args):
            policy, batch = args[i], int(args[i + 1])
            seq = 2048
            if i + 2 < len(args) and args[i + 2].isdigit():
                seq = int(args[i + 2])
                i += 1
            combos.append((policy, batch, seq))
            i += 2
    else:
        combos = [("save_attn", 6, 2048), ("save_attn", 8, 2048),
                  ("full", 6, 2048), ("save_attn", 4, 2048)]
    for policy, batch, seq in combos:
        try:
            run(policy, batch, seq)
        except Exception as e:  # noqa: BLE001
            msg = str(e)[:200].replace("\n", " ")
            print(f"policy={policy:<10s} b={batch} seq={seq}: FAILED {msg}",
                  flush=True)


if __name__ == "__main__":
    main()
