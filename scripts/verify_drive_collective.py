"""End-to-end drive of the host collective API through the real runtime."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import numpy as np

import ray_tpu
from ray_tpu.util import collective


@ray_tpu.remote(num_cpus=0.1)
class Rank:
    def __init__(self, ws, r):
        collective.init_collective_group(ws, r, group_name="vg")
        self.r = r

    def run_all(self):
        out = {}
        out["allreduce"] = collective.allreduce(
            np.full(4, self.r + 1, np.float32), group_name="vg").tolist()
        out["bcast"] = float(collective.broadcast(
            np.float32(self.r * 11), src_rank=2, group_name="vg"))
        gathered = collective.allgather(
            np.float32(self.r), group_name="vg")
        out["gather"] = [float(x) for x in gathered]
        collective.barrier(group_name="vg")
        return out


def main():
    ray_tpu.init(num_cpus=4)
    ranks = [Rank.remote(3, r) for r in range(3)]
    outs = ray_tpu.get([r.run_all.remote() for r in ranks], timeout=60)
    for o in outs:
        assert o["allreduce"] == [6.0] * 4, o
        assert o["bcast"] == 22.0, o
        assert o["gather"] == [0.0, 1.0, 2.0], o
    print("[1] allreduce/broadcast/allgather/barrier across 3 actors ok")
    # second round: same group, sequence counters advance
    outs = ray_tpu.get([r.run_all.remote() for r in ranks], timeout=60)
    assert all(o["allreduce"] == [6.0] * 4 for o in outs)
    print("[2] second round over same group ok")
    print("COLLECTIVE DRIVE OK")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
