"""Drive the multiprocessing.Pool + joblib backends end-to-end."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import ray_tpu  # noqa: E402


def main():
    ray_tpu.init(num_cpus=4)
    from ray_tpu.util.multiprocessing import Pool

    def cube(x):
        return x ** 3

    with Pool(processes=3) as p:
        out = p.map(cube, range(50))
        assert out == [i ** 3 for i in range(50)]
        assert p.starmap(pow, [(2, 5), (3, 2)]) == [32, 9]
        assert sorted(p.imap_unordered(cube, range(10))) == \
            sorted(i ** 3 for i in range(10))
    print("[1] Pool map/starmap/imap_unordered over cluster tasks OK")

    from joblib import Parallel, delayed, parallel_backend

    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    t0 = time.time()
    with parallel_backend("ray_tpu", n_jobs=4):
        res = Parallel()(delayed(cube)(i) for i in range(40))
    assert res == [i ** 3 for i in range(40)]
    print(f"[2] joblib backend: 40 delayed calls in {time.time()-t0:.2f}s")
    ray_tpu.shutdown()
    print("ALL OK")


if __name__ == "__main__":
    main()
