"""End-to-end drive of the runtime-env subsystem through the real
multi-process runtime: packaging, working_dir/py_modules shipping,
env_vars pools, pip validation failure fast-fail, job working_dir."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import tempfile  # noqa: E402
import time  # noqa: E402

import ray_tpu  # noqa: E402


def main():
    t0 = time.time()
    ray_tpu.init(num_cpus=4)

    with tempfile.TemporaryDirectory() as d:
        proj = os.path.join(d, "proj")
        os.makedirs(proj)
        with open(os.path.join(proj, "shipped_mod.py"), "w") as f:
            f.write("MAGIC = 'shipped-ok'\n")
        with open(os.path.join(proj, "asset.txt"), "w") as f:
            f.write("asset-body")

        # [1] working_dir ships: import + cwd file access in the worker.
        @ray_tpu.remote(runtime_env={"working_dir": proj})
        def use_wd():
            import shipped_mod

            return shipped_mod.MAGIC, open("asset.txt").read()

        assert ray_tpu.get(use_wd.remote()) == ("shipped-ok", "asset-body")
        print(f"[1] working_dir packaging + ship ok ({time.time()-t0:.1f}s)")

        # [2] env_vars pool separation.
        @ray_tpu.remote(runtime_env={"env_vars": {"DRIVE_VAR": "on"}})
        def with_var():
            return os.environ.get("DRIVE_VAR"), os.getpid()

        @ray_tpu.remote
        def without_var():
            return os.environ.get("DRIVE_VAR"), os.getpid()

        (v1, p1), (v2, p2) = ray_tpu.get(
            [with_var.remote(), without_var.remote()])
        assert v1 == "on" and v2 is None and p1 != p2
        print(f"[2] env_vars pool separation ok ({time.time()-t0:.1f}s)")

        # [3] pip validation: available passes, missing fails the task
        # (not a hang — broken-env fast fail).
        @ray_tpu.remote(runtime_env={"pip": ["numpy"]})
        def with_numpy():
            import numpy

            return numpy.__name__

        assert ray_tpu.get(with_numpy.remote()) == "numpy"

        @ray_tpu.remote(runtime_env={"pip": ["no_such_pkg_zz"]},
                        max_retries=0)
        def doomed():
            return 1

        try:
            ray_tpu.get(doomed.remote(), timeout=60)
            raise AssertionError("expected runtime_env failure")
        except Exception as e:
            assert "runtime_env" in str(e) or "no_such_pkg_zz" in str(e), e
        print(f"[3] pip validation + fast fail ok ({time.time()-t0:.1f}s)")

        # [4] job submission with a working_dir.
        from ray_tpu.job import JobSubmissionClient

        with open(os.path.join(proj, "entry.py"), "w") as f:
            f.write("print(open('asset.txt').read())\n")
        client = JobSubmissionClient()
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} entry.py",
            runtime_env={"working_dir": proj})
        deadline = time.time() + 60
        while time.time() < deadline:
            st = client.get_job_status(job_id)
            if st.value in ("SUCCEEDED", "FAILED", "STOPPED"):
                break
            time.sleep(0.25)
        assert st.value == "SUCCEEDED", (st, client.get_job_logs(job_id))
        assert "asset-body" in client.get_job_logs(job_id)
        print(f"[4] job working_dir ok ({time.time()-t0:.1f}s)")

    ray_tpu.shutdown()
    print("RUNTIME ENV DRIVE OK")


if __name__ == "__main__":
    main()
