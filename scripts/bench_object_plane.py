"""Object-plane fast-path probe: pull throughput (single-chunk vs
windowed), single-flight dedup fan-in, and locality on/off task latency.

Writes OBJ_BENCH.json at the repo root; tests/test_object_plane.py
asserts the acceptance thresholds against it (windowed >= 1.5x single
on a >= 64 MiB object; dedup fan-in of 8 consumers performs exactly one
wire pull).

The throughput rows pull from a chunk server in a SEPARATE process with
a simulated per-chunk transit latency (LATENCY_S, via rpc.Deferred +
timer so delayed chunks overlap like real wire transit): cross-host
object pulls pay an RTT per chunk when ping-ponging, and that gap —
not peak memcpy bandwidth — is what the in-flight window removes.  A
single-core loopback has neither RTT nor spare compute, so without the
modeled latency both windows measure the same kernel-copy ceiling.

Run:  python scripts/bench_object_plane.py
      RAY_TPU_BENCH_LATENCY_MS=0 python scripts/bench_object_plane.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHUNK = 8 << 20  # config.transfer_chunk_bytes default
WINDOW = 4       # config.pull_window default
SIZES = {"64MiB": 64 << 20, "256MiB": 256 << 20}
TRIALS = 3
# Simulated one-way transit per chunk (~an inter-zone RTT); override
# with RAY_TPU_BENCH_LATENCY_MS (0 = raw loopback).
LATENCY_S = float(os.environ.get("RAY_TPU_BENCH_LATENCY_MS", "15")) / 1e3


def _serve_forever(max_size: int, latency_s: float) -> None:
    """Child-process mode: serve fetch_chunk from a synthetic payload.
    Each chunk's response is delayed by latency_s on a timer (Deferred,
    so concurrent in-flight chunks overlap their transit exactly like a
    real wire — a blocking sleep in the handler would serialize them
    and hide the very effect being measured)."""
    from ray_tpu.core import rpc

    block = bytes(range(256)) * 4096  # 1 MiB
    payload = (block * ((max_size // len(block)) + 1))[:max_size]

    def handle(conn, msg):
        if msg.get("op") != "fetch_chunk":
            return None
        part = payload[msg["offset"]:msg["offset"] + msg["length"]]
        if latency_s <= 0:
            return part
        d = rpc.Deferred()
        threading.Timer(latency_s, d.resolve, args=(part,)).start()
        return d

    srv = rpc.Server(handle)
    print(srv.port, flush=True)
    threading.Event().wait()  # serve until killed


def _bench_pull_throughput() -> dict:
    from ray_tpu.core import rpc

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve",
         str(max(SIZES.values())), str(LATENCY_S)],
        stdout=subprocess.PIPE, text=True)
    port = int(proc.stdout.readline())
    rows = {}
    try:
        client = rpc.Client(f"127.0.0.1:{port}")
        # Warm both directions (connection, allocator, page cache).
        rpc.pull_object_chunked(client, "00" * 14, CHUNK, CHUNK, window=1)
        for label, size in SIZES.items():
            row = {}
            for name, window in (("single", 1), ("windowed", WINDOW)):
                best = 0.0
                for _ in range(TRIALS):
                    dest = bytearray(size)
                    t0 = time.perf_counter()
                    rpc.pull_object_chunked(client, "00" * 14, size,
                                            CHUNK, window=window,
                                            into=dest)
                    dt = time.perf_counter() - t0
                    best = max(best, size / dt / 1e6)
                    del dest
                row[f"{name}_MBps"] = round(best, 1)
            row["window"] = WINDOW
            row["chunk_MiB"] = CHUNK >> 20
            row["speedup"] = round(row["windowed_MBps"]
                                   / max(row["single_MBps"], 1e-9), 2)
            rows[label] = row
        client.close()
    finally:
        proc.kill()
        proc.wait()
    return rows


def _bench_dedup_fan_in() -> dict:
    """8 concurrent consumers of one remote object through the
    single-flight PullManager: count wire pulls at the server."""
    from ray_tpu.core import object_plane, rpc

    size = 64 << 20
    payload = os.urandom(1 << 20) * 64
    starts = []  # offset-0 requests == wire pulls begun
    lock = threading.Lock()

    def handle(conn, msg):
        if msg.get("op") != "fetch_chunk":
            return None
        if msg["offset"] == 0:
            with lock:
                starts.append(1)
        return payload[msg["offset"]:msg["offset"] + msg["length"]]

    srv = rpc.Server(handle)
    client = rpc.Client(f"127.0.0.1:{srv.port}")
    pm = object_plane.PullManager()
    results = []
    errors = []
    barrier = threading.Barrier(8)

    def consumer():
        barrier.wait(timeout=30.0)
        try:
            data = pm.pull("ab" * 14, lambda: rpc.pull_object_chunked(
                client, "ab" * 14, size, CHUNK, window=WINDOW))
            results.append(len(data))
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    t0 = time.perf_counter()
    threads = [threading.Thread(target=consumer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    dt = time.perf_counter() - t0
    client.close()
    srv.stop()
    return {
        "consumers": 8,
        "object_MiB": size >> 20,
        "wire_pulls": len(starts),
        "errors": errors,
        "all_served": results == [size] * 8,
        "fan_in_s": round(dt, 3),
    }


def _bench_locality_latency() -> dict:
    """End-to-end task latency with a 16 MiB shm arg, locality tie-break
    on vs off, on a 2-node fake cluster.  Informational (fake-cluster
    nodes share one arena, so the byte movement is identical either
    way); the acceptance gates live on the rows above."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    rows = {}
    try:
        cluster.add_node(num_cpus=2, node_id="n2")
        blob = ray_tpu.put(os.urandom(16 << 20))

        @ray_tpu.remote
        def touch(x):
            return len(x) > 0

        ray_tpu.get([touch.remote(blob) for _ in range(4)])  # warm workers
        for key, env in (("on_s", None), ("off_s", "1")):
            if env is None:
                os.environ.pop("RAY_TPU_NO_LOCALITY", None)
            else:
                os.environ["RAY_TPU_NO_LOCALITY"] = env
            t0 = time.perf_counter()
            ray_tpu.get([touch.remote(blob) for _ in range(30)],
                        timeout=120)
            rows[key] = round(time.perf_counter() - t0, 3)
        rows["tasks"] = 30
        rows["arg_MiB"] = 16
    finally:
        os.environ.pop("RAY_TPU_NO_LOCALITY", None)
        cluster.shutdown()
    return rows


def main() -> int:
    if "--serve" in sys.argv:
        i = sys.argv.index("--serve")
        _serve_forever(int(sys.argv[i + 1]), float(sys.argv[i + 2]))
        return 0
    doc = {
        "pull_throughput": _bench_pull_throughput(),
        "dedup_fan_in": _bench_dedup_fan_in(),
        "locality_task_latency": _bench_locality_latency(),
        "meta": {
            "chunk_bytes": CHUNK,
            "window": WINDOW,
            "trials": TRIALS,
            "simulated_transit_ms": LATENCY_S * 1e3,
            "note": "server in a separate process; per-chunk transit "
                    "latency simulated on a timer (Deferred) so "
                    "in-flight chunks overlap like real wire transit; "
                    "MBps = best of trials",
        },
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "OBJ_BENCH.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
