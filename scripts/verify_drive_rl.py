"""End-to-end drive of the RL layer through the real runtime.

Covers: PPO local mode learning on CartPole, remote env runners + remote
learners (full multi-process path), runner kill + restart, checkpoint
save/restore.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.rl.algorithms import PPOConfig  # noqa: E402


def main():
    t0 = time.time()

    # [1] Local-mode PPO learns CartPole.
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8)
              .training(train_batch_size=2048, lr=3e-4, minibatch_size=256,
                        num_epochs=6, entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    first = algo.step()["episode_return_mean"]
    last = first
    for _ in range(11):
        last = algo.step()["episode_return_mean"]
    assert last > first + 20, (first, last)
    print(f"[1] local PPO learns: {first:.1f} -> {last:.1f} "
          f"({time.time()-t0:.1f}s)")

    # [2] checkpoint roundtrip.
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        algo.save_checkpoint(d)
        algo2 = (PPOConfig().environment("CartPole-v1")
                 .training(train_batch_size=256, minibatch_size=64,
                           num_epochs=1)).build()
        algo2.load_checkpoint(d)
        w1 = algo.learner_group.get_weights()
        w2 = algo2.learner_group.get_weights()
        np.testing.assert_allclose(
            np.asarray(w1["pi"]["layers"][0]["w"]),
            np.asarray(w2["pi"]["layers"][0]["w"]))
        algo2.stop()
    algo.stop()
    print(f"[2] checkpoint roundtrip ok ({time.time()-t0:.1f}s)")

    # [3] Full multi-process path: remote runners + remote learners.
    ray_tpu.init(num_cpus=6)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
              .learners(num_learners=2)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=2))
    algo = config.build()
    r = algo.step()
    assert r["num_env_steps_trained"] >= 256, r
    print(f"[3] remote runners+learners step ok ({time.time()-t0:.1f}s)")

    # [4] kill an env runner mid-run; group restarts it.
    ray_tpu.kill(algo.env_runner_group.remote_runners[1])
    r = algo.step()
    assert r["num_env_steps_trained"] >= 256, r
    print(f"[4] runner kill + restart ok ({time.time()-t0:.1f}s)")
    algo.stop()

    # [5] DQN with remote env runners: QNetworkSpec ships to actors,
    # replay + target sync + greedy evaluate() work end to end.
    from ray_tpu.rl.algorithms import DQNConfig
    dqn = (DQNConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=64)
           .training(train_batch_size=32, hidden_sizes=(32,),
                     num_steps_sampled_before_learning_starts=100,
                     training_intensity=2.0)
           .debugging(seed=0)).build()
    for _ in range(4):
        r = dqn.step()
    assert r.get("num_grad_steps", 0) > 0, r
    ev = dqn.evaluate(num_episodes=2)
    # A multi-env runner can finish several episodes in one vector step.
    assert ev["evaluation/num_episodes"] >= 2
    dqn.stop()
    print(f"[5] DQN remote runners + evaluate ok ({time.time()-t0:.1f}s)")

    # [6] APPO: async in-flight sampling over remote runners.
    from ray_tpu.rl.algorithms import APPOConfig
    appo = (APPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                         rollout_fragment_length=64)
            .training(train_batch_size=128)
            .debugging(seed=0)).build()
    trained = 0
    for _ in range(5):
        trained += appo.step().get("num_env_steps_trained", 0)
    assert trained > 0
    appo.stop()
    print(f"[6] APPO async sampling ok ({time.time()-t0:.1f}s)")

    # [7] SAC smoke on Pendulum (continuous actions, local mode).
    from ray_tpu.rl.algorithms import SACConfig
    sac = (SACConfig().environment("Pendulum-v1")
           .env_runners(num_envs_per_env_runner=2,
                        rollout_fragment_length=64)
           .training(train_batch_size=32, hidden_sizes=(32,),
                     num_steps_sampled_before_learning_starts=64,
                     training_intensity=1.0)
           .debugging(seed=0)).build()
    r = sac.step()
    r = sac.step()
    assert "critic_loss" in r, r
    sac.stop()
    print(f"[7] SAC continuous-control step ok ({time.time()-t0:.1f}s)")

    # [8] BC from offline episodes.
    from ray_tpu.rl.algorithms import BCConfig
    from ray_tpu.rl.episode import SingleAgentEpisode
    rng = np.random.default_rng(0)
    eps = []
    for _ in range(4):
        ep = SingleAgentEpisode()
        obs = rng.normal(size=(11, 4)).astype(np.float32)
        ep.add_reset(obs[0])
        for t in range(10):
            ep.add_step(obs[t + 1], int(obs[t][0] > 0), 1.0,
                        terminated=t == 9)
        eps.append(ep)
    bc = (BCConfig().environment("CartPole-v1")
          .offline_data(input_episodes=eps)
          .training(train_batch_size=32, num_sgd_iter=4)).build()
    r = bc.step()
    assert "bc_logp" in r, r
    bc.stop()
    print(f"[8] BC offline training ok ({time.time()-t0:.1f}s)")

    ray_tpu.shutdown()
    

if __name__ == "__main__":
    main()


def drive_multi_agent():
    """Multi-policy PPO on a 2-agent coordination game: returns climb
    and both policies train."""
    import numpy as np

    from ray_tpu.rl.multi_agent import MultiAgentEnv, MultiAgentPPOConfig

    class TargetMatch(MultiAgentEnv):
        N = 4
        possible_agents = ["a0", "a1"]
        agent_specs = {"a0": (4, 4, True), "a1": (4, 4, True)}

        def __init__(self, seed: int = 0):
            self._rng = np.random.default_rng(seed)
            self._t = 0

        def _obs(self):
            self._targets = {a: int(self._rng.integers(0, self.N))
                             for a in self.possible_agents}
            return {a: np.eye(self.N, dtype=np.float32)[t]
                    for a, t in self._targets.items()}

        def reset(self, *, seed=None):
            self._t = 0
            return self._obs(), {}

        def step(self, action_dict):
            rewards = {a: float(int(action_dict[a]) == self._targets[a])
                       for a in action_dict}
            self._t += 1
            done = self._t >= 6
            obs = {} if done else self._obs()
            flags = {a: done for a in self.possible_agents}
            flags["__all__"] = done
            return obs, rewards, flags, {"__all__": False}, {}

    cfg = MultiAgentPPOConfig().environment(env_fn=TargetMatch)
    cfg.train_batch_size = 256
    cfg.minibatch_size = 128
    cfg.num_epochs = 6
    cfg.lr = 5e-3
    cfg = cfg.multi_agent(
        policies={"p0": None, "p1": None},
        policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1")
    algo = cfg.build()
    try:
        first = algo.train().get("episode_return_mean", 0.0)
        for _ in range(7):
            res = algo.train()
        final = res["episode_return_mean"]
        assert final > 3.0, (first, final)
        print(f"[MA] multi-policy PPO: return {first:.2f} -> {final:.2f} "
              f"(max 6.0), policies trained: "
              f"{sorted({k.split('/')[0] for k in res if '/' in k})}")
    finally:
        algo.stop()


def drive_catalog_lstm():
    """Catalog model_config path (rl/catalog.py) + recurrent module:
    use_lstm PPO beats the 0.5 memoryless ceiling on RecallEnv."""
    from ray_tpu.rl import RecurrentRLModuleSpec
    from ray_tpu.rl.algorithms import PPOConfig
    from ray_tpu.rl.envs import RecallEnv

    cfg = (PPOConfig()
           .environment(env_fn=lambda: RecallEnv(length=4))
           .env_runners(num_envs_per_env_runner=8)
           .rl_module(model_config={"use_lstm": True,
                                    "lstm_cell_size": 32,
                                    "fcnet_hiddens": [32],
                                    "max_seq_len": 8})
           .training(train_batch_size=512, minibatch_size=256,
                     lr=3e-3, num_epochs=6, entropy_coeff=0.01)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        assert isinstance(algo.env_runner_group.spec,
                          RecurrentRLModuleSpec)
        best = 0.0
        for _ in range(20):
            best = max(best, algo.step().get("episode_return_mean", 0.0))
            if best > 0.8:
                break
        assert best > 0.8, best
        print(f"[LSTM] catalog use_lstm PPO: RecallEnv return {best:.2f} "
              "(memoryless ceiling 0.5)")
    finally:
        algo.stop()


drive_multi_agent()
drive_catalog_lstm()
print("RL DRIVE OK")
