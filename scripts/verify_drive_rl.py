"""End-to-end drive of the RL layer through the real runtime.

Covers: PPO local mode learning on CartPole, remote env runners + remote
learners (full multi-process path), runner kill + restart, checkpoint
save/restore.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.rl.algorithms import PPOConfig  # noqa: E402


def main():
    t0 = time.time()

    # [1] Local-mode PPO learns CartPole.
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8)
              .training(train_batch_size=2048, lr=3e-4, minibatch_size=256,
                        num_epochs=6, entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    first = algo.step()["episode_return_mean"]
    last = first
    for _ in range(11):
        last = algo.step()["episode_return_mean"]
    assert last > first + 20, (first, last)
    print(f"[1] local PPO learns: {first:.1f} -> {last:.1f} "
          f"({time.time()-t0:.1f}s)")

    # [2] checkpoint roundtrip.
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        algo.save_checkpoint(d)
        algo2 = (PPOConfig().environment("CartPole-v1")
                 .training(train_batch_size=256, minibatch_size=64,
                           num_epochs=1)).build()
        algo2.load_checkpoint(d)
        w1 = algo.learner_group.get_weights()
        w2 = algo2.learner_group.get_weights()
        np.testing.assert_allclose(
            np.asarray(w1["pi"]["layers"][0]["w"]),
            np.asarray(w2["pi"]["layers"][0]["w"]))
        algo2.stop()
    algo.stop()
    print(f"[2] checkpoint roundtrip ok ({time.time()-t0:.1f}s)")

    # [3] Full multi-process path: remote runners + remote learners.
    ray_tpu.init(num_cpus=6)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
              .learners(num_learners=2)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=2))
    algo = config.build()
    r = algo.step()
    assert r["num_env_steps_trained"] >= 256, r
    print(f"[3] remote runners+learners step ok ({time.time()-t0:.1f}s)")

    # [4] kill an env runner mid-run; group restarts it.
    ray_tpu.kill(algo.env_runner_group.remote_runners[1])
    r = algo.step()
    assert r["num_env_steps_trained"] >= 256, r
    print(f"[4] runner kill + restart ok ({time.time()-t0:.1f}s)")

    algo.stop()
    ray_tpu.shutdown()
    print("RL DRIVE OK")


if __name__ == "__main__":
    main()
