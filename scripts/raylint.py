#!/usr/bin/env python
"""raylint — thin wrapper so the suite runs as a script from anywhere:

    python scripts/raylint.py [--passes knobs,except,...] [...]

is exactly ``python -m ray_tpu.analysis`` with the repo on sys.path.
See README "Static analysis" for the pass list, the suppression
comment syntax, and when (not) to touch the baseline.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ray_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
