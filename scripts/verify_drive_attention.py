"""Verify driver: flash attention bf16-MXU kernel vs dense reference ON CHIP.

Checks (real TPU through the tunnel):
  1. fwd values match attention_reference within bf16 tolerance,
     at both bench shapes and a decode-style sq<sk shape;
  2. grads (dq, dk, dv) match within tolerance;
  3. the chunked (offset-aware) kernel agrees with the plain one.
"""
import sys

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import (attention_reference, flash_attention,
                                   flash_attention_chunk)

ok = True


def check(name, a, b, tol):
    global ok
    err = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) or 1.0
    rel = err / scale
    status = "OK" if rel < tol else "FAIL"
    if rel >= tol:
        ok = False
    print(f"  {name}: max_abs={err:.4g} rel={rel:.4g} [{status}]")


for b, sq, sk, h, d in ((2, 512, 512, 4, 128), (1, 1024, 1024, 2, 128),
                        (2, 256, 1024, 2, 128)):
    print(f"shape b{b} sq{sq} sk{sk} h{h} d{d}")
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, sq, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, sk, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, sk, h, d), jnp.bfloat16)
    out_f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=256, block_k=256))(q, k, v)
    out_r = jax.jit(lambda q, k, v: attention_reference(
        q, k, v, causal=True))(q, k, v)
    check("fwd", out_f, out_r, 2e-2)

    if sq == sk:
        def loss_f(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=256,
                                   block_k=256).astype(jnp.float32).sum()

        def loss_r(q, k, v):
            return attention_reference(
                q, k, v, causal=True).astype(jnp.float32).sum()

        gf = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(q, k, v)
        for name, a, r in zip(("dq", "dk", "dv"), gf, gr):
            check(name, a, r, 4e-2)

# chunk kernel vs plain (same global positions)
b, s, h, d = 2, 1024, 2, 128
ks = jax.random.split(jax.random.key(1), 3)
q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
out_c, _ = jax.jit(lambda q, k, v: flash_attention_chunk(
    q, k, v, 0, 0, causal=True, block_q=256, block_k=256))(q, k, v)
out_p = jax.jit(lambda q, k, v: flash_attention(
    q, k, v, causal=True, block_q=256, block_k=256))(q, k, v)
print("chunk-vs-plain")
check("chunk", out_c, out_p, 1e-3)

print("ALL OK" if ok else "FAILURES", flush=True)
sys.exit(0 if ok else 1)
