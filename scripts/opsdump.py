#!/usr/bin/env python
"""opsdump — export a past window of the durable ops journal as a
Perfetto-loadable chrome trace.

The live dashboard (`/api/trace`) can only show what the current head
holds in memory; this reads the on-disk journal segments directly
(no cluster required — works on a dead cluster's journal dir), merges
the "spans", "flight", "metrics" and "device" streams, and writes one
chrome trace JSON:

    python scripts/opsdump.py --dir /var/ray_tpu/ops \\
        --last 3600 --out trace.json
    python scripts/opsdump.py --dir $RAY_TPU_OPS_JOURNAL_DIR --stats

Lanes follow the dashboard convention: harvested spans render on each
worker's OS-pid lane, flight-recorder events are instant markers on a
per-category lane, and scalar metrics become counter tracks.  Serve
request-journey spans (`serve.*`, tagged with a trace id) get their
own process with one named lane per request, so each journey's phases
read as nested slices on a single row.  Device-plane records become
roofline/MFU counter tracks plus instant recompile markers on a
"device plane" process.  `--since` / `--until` take epoch seconds;
`--last N` means "the last N seconds".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ray_tpu.util import journal  # noqa: E402
from ray_tpu.util.tracing import (  # noqa: E402
    span_row_to_dict,
    spans_to_chrome_events,
)

STREAMS = ("spans", "flight", "metrics", "device")
# One synthetic chrome pid per flight-recorder category lane.
_FLIGHT_PID = 0
# Synthetic process holding the per-request serve lanes: one named
# thread per trace id, so each request's journey (queue → prefill →
# handoff_pull → decode → stream) reads as nested slices on its own
# row even when the phases ran in different OS processes.
_SERVE_PID = 1 << 22
# Synthetic process for device-plane telemetry (roofline/MFU counter
# tracks + recompile instant markers), one thread lane per OS pid.
_DEVICE_PID = (1 << 22) + 1


def serve_request_events(spans: List[dict]) -> List[Dict[str, Any]]:
    """serve.* spans grouped by trace id → one named lane per request."""
    by_req: Dict[str, List[dict]] = {}
    for s in spans:
        by_req.setdefault(s.get("trace_id", ""), []).append(s)
    events: List[Dict[str, Any]] = []
    lanes = sorted(by_req.items(),
                   key=lambda kv: min(x["start"] for x in kv[1]))
    for tid, (trace_id, group) in enumerate(lanes):
        for s in group:
            events.append({
                "cat": "serve", "name": s["name"], "ph": "X",
                "pid": _SERVE_PID, "tid": tid,
                "ts": s["start"] * 1e6,
                "dur": max(0.0, s["end"] - s["start"]) * 1e6,
                "args": {**s["attributes"], "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         "trace_id": trace_id},
            })
        events.append({"ph": "M", "pid": _SERVE_PID, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": f"req {trace_id[:8] or '?'}"}})
    if events:
        events.append({"ph": "M", "pid": _SERVE_PID,
                       "name": "process_name",
                       "args": {"name": "serve requests"}})
    return events


def span_events(envs: List[dict]) -> List[Dict[str, Any]]:
    """Journal span rows → X slices, one lane per (pid, worker);
    serve-plane request spans additionally fan out by trace id."""
    by_lane: Dict[tuple, List[dict]] = {}
    serve_spans: List[dict] = []
    for env in envs:
        row = env.get("d")
        if not isinstance(row, list) or len(row) < 7:
            continue
        s = span_row_to_dict(row)
        if s["name"].startswith("serve.") and s.get("trace_id"):
            serve_spans.append(s)
            continue
        key = (int(s.get("pid") or 0), s.get("worker", ""))
        by_lane.setdefault(key, []).append(s)
    events: List[Dict[str, Any]] = []
    for (pid, whex), spans in sorted(by_lane.items()):
        events.extend(spans_to_chrome_events(
            spans, pid=pid or 1,
            process_name=f"worker spans {whex[:8]}" if whex
            else "driver spans",
            sort_index=pid or 1))
    events.extend(serve_request_events(serve_spans))
    return events


def flight_events(envs: List[dict]) -> List[Dict[str, Any]]:
    """Flight-recorder events → instant markers, one thread lane per
    category (wire/scheduler/object/health)."""
    events: List[Dict[str, Any]] = []
    lanes: Dict[str, int] = {}
    for env in envs:
        ev = env.get("d")
        if not isinstance(ev, dict) or "ts" not in ev:
            continue
        cat = str(ev.get("category", "?"))
        tid = lanes.setdefault(cat, len(lanes))
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "category", "event")}
        events.append({
            "cat": "flight", "name": str(ev.get("event", "?")),
            "ph": "i", "s": "t", "pid": _FLIGHT_PID, "tid": tid,
            "ts": float(ev["ts"]) * 1e6, "args": args})
    if events:
        events.append({"ph": "M", "pid": _FLIGHT_PID,
                       "name": "process_name",
                       "args": {"name": "flight recorder"}})
        for cat, tid in lanes.items():
            events.append({"ph": "M", "pid": _FLIGHT_PID, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": cat}})
    return events


def metric_events(envs: List[dict]) -> List[Dict[str, Any]]:
    """Metrics snapshots → counter tracks (scalar series summed over
    tags; histogram series plot their sample count)."""
    events: List[Dict[str, Any]] = []
    for env in envs:
        rec = env.get("d")
        if not isinstance(rec, dict):
            continue
        ts = float(env.get("t", 0.0)) * 1e6
        pid = int(env.get("p", 0))
        for snap in rec.get("snapshots", []):
            total = 0.0
            for _, val in snap.get("series", []):
                if isinstance(val, (int, float)):
                    total += float(val)
                elif isinstance(val, list) and len(val) == 3:
                    total += float(val[2])  # histogram count
            events.append({
                "cat": "metrics", "name": snap.get("name", "?"),
                "ph": "C", "pid": pid, "tid": 0, "ts": ts,
                "args": {"value": total}})
    return events


def device_events(envs: List[dict]) -> List[Dict[str, Any]]:
    """Device journal records → counter tracks for the continuous
    roofline/MFU step windows and instant markers for compile events
    (a recompile storm reads as a burst of markers over a sagging
    roofline track)."""
    events: List[Dict[str, Any]] = []
    lanes: Dict[int, int] = {}
    for env in envs:
        rec = env.get("d")
        if not isinstance(rec, dict):
            continue
        pid = int(env.get("p", 0))
        tid = lanes.setdefault(pid, len(lanes))
        ts = float(rec.get("ts") or env.get("t", 0.0)) * 1e6
        kind = rec.get("kind")
        if kind == "step":
            plane = rec.get("plane", "?")
            for field in ("roofline_fraction", "mfu"):
                val = rec.get(field)
                if isinstance(val, (int, float)):
                    events.append({
                        "cat": "device",
                        "name": f"{field}[{plane}]",
                        "ph": "C", "pid": _DEVICE_PID, "tid": tid,
                        "ts": ts, "args": {"value": float(val)}})
            tok_s = rec.get("tokens_per_s")
            if isinstance(tok_s, (int, float)):
                events.append({
                    "cat": "device", "name": f"tokens_per_s[{plane}]",
                    "ph": "C", "pid": _DEVICE_PID, "tid": tid,
                    "ts": ts, "args": {"value": float(tok_s)}})
        elif kind == "compile":
            args = {k: rec.get(k) for k in (
                "wall_s", "shapes", "count", "after_warmup")}
            events.append({
                "cat": "device",
                "name": f"compile {rec.get('function', '?')}",
                "ph": "i", "s": "t", "pid": _DEVICE_PID, "tid": tid,
                "ts": ts, "args": args})
    if events:
        events.append({"ph": "M", "pid": _DEVICE_PID,
                       "name": "process_name",
                       "args": {"name": "device plane"}})
        for pid, tid in lanes.items():
            events.append({"ph": "M", "pid": _DEVICE_PID, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": f"pid {pid}"}})
    return events


def dump_stats(directory: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {"dir": directory}
    for stream in STREAMS:
        segs = journal.list_segments(directory, stream)
        envs = journal.replay(directory, stream)
        out[stream] = {
            "segments": len(segs),
            "bytes": sum(size for _, _, _, size in segs),
            "records": len(envs),
            "first_ts": envs[0]["t"] if envs else 0.0,
            "last_ts": envs[-1]["t"] if envs else 0.0,
        }
    return out


def build_trace(directory: str, since: float = 0.0,
                until: float = 0.0,
                streams=STREAMS) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    if "spans" in streams:
        events.extend(span_events(
            journal.replay(directory, "spans", since=since,
                           until=until)))
    if "flight" in streams:
        events.extend(flight_events(
            journal.replay(directory, "flight", since=since,
                           until=until)))
    if "metrics" in streams:
        events.extend(metric_events(
            journal.replay(directory, "metrics", since=since,
                           until=until)))
    if "device" in streams:
        events.extend(device_events(
            journal.replay(directory, "device", since=since,
                           until=until)))
    return events


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Export a window of the ops journal as a chrome "
                    "trace (load in Perfetto / chrome://tracing).")
    ap.add_argument("--dir", default=os.environ.get(
        "RAY_TPU_OPS_JOURNAL_DIR", ""),
        help="journal directory (default: $RAY_TPU_OPS_JOURNAL_DIR)")
    ap.add_argument("--since", type=float, default=0.0,
                    help="window start (epoch seconds)")
    ap.add_argument("--until", type=float, default=0.0,
                    help="window end (epoch seconds)")
    ap.add_argument("--last", type=float, default=0.0,
                    help="shorthand: window = the last N seconds")
    ap.add_argument("--streams", default=",".join(STREAMS),
                    help="comma list of streams to include "
                         f"(default: {','.join(STREAMS)})")
    ap.add_argument("--out", default="",
                    help="output file (default: stdout)")
    ap.add_argument("--stats", action="store_true",
                    help="print per-stream segment/record counts "
                         "instead of a trace")
    args = ap.parse_args(argv)
    if not args.dir:
        ap.error("--dir required (or set RAY_TPU_OPS_JOURNAL_DIR)")
    since = args.since
    if args.last > 0:
        since = max(since, time.time() - args.last)
    if args.stats:
        print(json.dumps(dump_stats(args.dir), indent=2))
        return 0
    streams = tuple(s.strip() for s in args.streams.split(",")
                    if s.strip())
    events = build_trace(args.dir, since=since, until=args.until,
                         streams=streams)
    payload = json.dumps({"traceEvents": events}, default=str)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(payload)
        print(f"wrote {len(events)} events -> {args.out}",
              file=sys.stderr)
    else:
        print(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
