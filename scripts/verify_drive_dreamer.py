"""Drive DreamerV3 end-to-end through the public API: recurrent acting,
sequence replay, world-model + actor-critic updates, checkpoint
roundtrip, evaluation."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # dev env exports =axon (TPU tunnel)
os.environ.setdefault("RAY_TPU_CHIPS", "none")

import jax  # noqa: E402

# The dev sitecustomize re-points jax at the axon TPU tunnel at
# interpreter start, overriding the env var; force CPU back.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    import tempfile

    from ray_tpu.rl.algorithms import DreamerV3Config

    cfg = DreamerV3Config().environment("CartPole-v1")
    cfg.deter_dim = 32; cfg.stoch_vars = 4; cfg.stoch_classes = 4
    cfg.units = 32; cfg.mlp_layers = 1
    cfg.batch_size_B = 4; cfg.batch_length_T = 8; cfg.horizon = 5
    cfg.rollout_fragment_length = 32
    cfg.num_steps_sampled_before_learning_starts = 64
    cfg.training_ratio = 8.0
    algo = cfg.build()
    t0 = time.time()
    for i in range(5):
        res = algo.train()
    assert np.isfinite(res["wm_loss"]), res
    print(f"[1] 5 iters in {time.time() - t0:.1f}s  "
          f"wm_loss={res['wm_loss']:.2f} entropy={res['entropy']:.2f} "
          f"return={res.get('episode_return_mean'):.1f}")

    with tempfile.TemporaryDirectory() as d:
        algo.save_checkpoint(d)
        it = algo.iteration
        algo.load_checkpoint(d)
        assert algo.iteration == it
    print("[2] checkpoint save/load roundtrip ok")

    ev = algo.evaluate(num_episodes=2)
    assert ev["evaluation/num_episodes"] == 2
    print(f"[3] eval return={ev['evaluation/episode_return_mean']:.1f}")
    res = algo.train()  # training continues after eval + restore
    assert np.isfinite(res["wm_loss"])
    print("[4] training continues after eval/restore")
    algo.stop()
    print("ALL OK")


if __name__ == "__main__":
    main()
