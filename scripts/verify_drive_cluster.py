"""End-to-end drive: multi-node cluster, placement groups, cancel."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")
import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import placement_group, remove_placement_group, \
    PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy

c = Cluster(head_node_args={"num_cpus": 2})
print("[1] head up:", ray_tpu.cluster_resources())
c.add_node(num_cpus=4, node_id="n2")
print("[2] added n2:", ray_tpu.cluster_resources())

# PG spanning both nodes
pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
print("[3] strict-spread pg ready:", ray_tpu.get(pg.ready(), timeout=15))
print("    bundles on:", sorted({b["node_id"] for b in pg.state()["bundles"]}))

@ray_tpu.remote(num_cpus=2, scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=1))
def in_bundle():
    import os
    return os.getpid()
print("[4] task in bundle 1 pid:", ray_tpu.get(in_bundle.remote(), timeout=20))

# cancel running
@ray_tpu.remote
def spin():
    time.sleep(60)
r = spin.remote(); time.sleep(0.7)
print("[5] cancel running:", ray_tpu.cancel(r, force=True))
try:
    ray_tpu.get(r, timeout=10); print("[5] FAIL")
except (ray_tpu.TaskCancelledError, ray_tpu.WorkerCrashedError) as e:
    print("[5] raises", type(e).__name__)

# node kill with actor restart
@ray_tpu.remote(max_restarts=1, scheduling_strategy=NodeAffinitySchedulingStrategy(node_id="n2", soft=True))
class S:
    def __init__(self): self.v = 0
    def bump(self): self.v += 1; return self.v
a = S.remote()
print("[6] actor on n2:", ray_tpu.get(a.bump.remote(), timeout=20))
c.remove_node("n2")
deadline = time.time() + 20
while True:
    try:
        v = ray_tpu.get(a.bump.remote(), timeout=5); break
    except ray_tpu.ActorError:
        if time.time() > deadline: raise
        time.sleep(0.2)
print("[6] after node kill, restarted actor:", v)

# PROBES
try:
    placement_group([{"CPU": 1}], strategy="BANANAS")
except ValueError as e:
    print("[P1] bad strategy -> ValueError")
pg2 = placement_group([{"CPU": 99}])
print("[P2] infeasible pg wait(0.3):", pg2.wait(0.3), "state:", pg2.state()["state"])
remove_placement_group(pg2)
print("[P3] remove pending pg ok; state:", pg2.state()["state"])
print("[P4] remove same pg twice:", end=" ")
remove_placement_group(pg2); print("no crash")
print("[P5] cancel same ref twice:", ray_tpu.cancel(r, force=True))
remove_placement_group(pg)
print("[7] available after all removals:", ray_tpu.available_resources())
c.shutdown()


def drive_node_labels():
    """NodeLabelSchedulingStrategy: hard pin + pending-until-join."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import NodeLabelSchedulingStrategy

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2, labels={"slice": "s0"})
        target = cluster.add_node(num_cpus=2, labels={"slice": "s1"})

        @ray_tpu.remote(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"slice": "s1"}))
        def where():
            return ray_tpu.get_runtime_context().node_id

        assert ray_tpu.get(where.remote(), timeout=30) == target

        @ray_tpu.remote(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"slice": "s9"}))
        def later():
            return ray_tpu.get_runtime_context().node_id

        ref = later.remote()
        ready, _ = ray_tpu.wait([ref], timeout=0.5)
        assert not ready  # pending: no s9 node yet
        joined = cluster.add_node(num_cpus=1, labels={"slice": "s9"})
        assert ray_tpu.get(ref, timeout=30) == joined
        print("[labels] hard label pin + pending-until-node-joins OK")
    finally:
        cluster.shutdown()


drive_node_labels()
print("ALL OK")
