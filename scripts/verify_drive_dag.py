"""End-to-end drive of DAG authoring, compiled-DAG channels, and workflows."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RAY_TPU_CHIPS", "none")
os.environ.setdefault("RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/verify-wf")

import shutil
import time

import numpy as np

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


@ray_tpu.remote
def double(x):
    return 2 * x


@ray_tpu.remote(num_cpus=0.1)
class Stage:
    def __init__(self, scale):
        self.scale = scale

    def fwd(self, x):
        return self.scale * x


def main():
    shutil.rmtree("/tmp/ray_tpu/verify-wf", ignore_errors=True)
    ray_tpu.init(num_cpus=4)

    # interpreted DAG
    with InputNode() as inp:
        dag = double.bind(double.bind(inp))
    assert dag.execute(3) == 12
    print("[1] interpreted dag ok")

    # compiled pipeline with throughput check
    a, b = Stage.remote(2), Stage.remote(5)
    with InputNode() as inp:
        cdag = b.fwd.bind(a.fwd.bind(inp))
    compiled = cdag.experimental_compile()
    n = 200
    t0 = time.perf_counter()
    refs = [compiled.execute(i) for i in range(20)]
    outs = [r.get(timeout=30) for r in refs]
    warm = time.perf_counter() - t0
    assert outs == [10 * i for i in range(20)], outs[:5]
    t0 = time.perf_counter()
    refs = [compiled.execute(i) for i in range(n)]
    outs = [r.get(timeout=60) for r in refs]
    dt = time.perf_counter() - t0
    assert outs[-1] == 10 * (n - 1)
    print(f"[2] compiled pipeline: {n} executions in {dt*1000:.1f}ms "
          f"({n/dt:.0f}/s, warmup {warm*1000:.0f}ms)")
    compiled.teardown()

    # workflow with checkpoint/resume visibility
    with InputNode() as inp:
        wdag = double.bind(double.bind(inp))
    out = workflow.run(wdag, workflow_id="verify-wf-1", workflow_input=7,
                       timeout=30)
    assert out == 28
    st = workflow.get_status("verify-wf-1")
    assert st == workflow.WorkflowStatus.SUCCESSFUL, st
    assert ("verify-wf-1", st) in workflow.list_all()
    print("[3] workflow run + status + list ok")

    ray_tpu.shutdown()
    print("DAG DRIVE OK")


if __name__ == "__main__":
    main()
