"""Per-step wall-time trace of the 128+128 bench shape."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from ray_tpu.models import transformer as tfm
from ray_tpu.serve.llm_engine import LLMEngine


def main():
    config = tfm.TransformerConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=16, num_kv_heads=4,
        max_seq_len=2048, remat=False)
    eng = LLMEngine(config, page_size=128, num_pages=320,
                    max_batch=128, multi_step=32)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config.vocab_size, 128).tolist()
               for _ in range(128)]
    warm = [rng.integers(1, config.vocab_size, 128).tolist()
            for _ in range(128)]
    t0 = time.perf_counter()
    eng.generate(warm, max_new_tokens=128)
    print(f"warm done {time.perf_counter()-t0:.1f}s", flush=True)

    t0 = time.perf_counter()
    for p in prompts:
        eng.add_request(p, max_new_tokens=128)
    results = {}
    step = 0
    while eng.has_work():
        ts = time.perf_counter()
        nw, ni = len(eng.waiting), len(eng._inflight)
        done = eng.step()
        te = time.perf_counter()
        results.update(done)
        print(f"step {step}: {te-ts:7.3f}s  waiting {nw}->"
              f"{len(eng.waiting)}  inflight {ni}->"
              f"{len(eng._inflight)}  done {len(done)}  "
              f"t={te-t0:.3f}", flush=True)
        step += 1
    print(f"total {time.perf_counter()-t0:.2f}s  "
          f"requests {len(results)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
