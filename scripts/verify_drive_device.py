"""End-to-end drive of the device-plane observability surface (PR 19):
a real multi-process cluster, a remote task churning XLA shapes, the
profile sampler carrying device fields + recompile counts to the head,
the watchdog flagging the storm and an injected HBM watermark, the
dashboard answering /api/device, the serve engine emitting continuous
roofline/MFU, and opsdump rendering the journal's device stream.

Run: JAX_PLATFORMS=cpu python scripts/verify_drive_device.py
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_CHIPS", "none")
os.environ["RAY_TPU_WATCHDOG_INTERVAL_S"] = "0.3"
os.environ["RAY_TPU_DEVICE_RECOMPILE_MAX"] = "2"
_journal_dir = tempfile.mkdtemp(prefix="rt-device-drive-")
os.environ["RAY_TPU_OPS_JOURNAL_DIR"] = _journal_dir

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu  # noqa: E402
from ray_tpu.util import device_stats, flight_recorder, journal  # noqa: E402


def main() -> int:
    t0 = time.time()
    rt = ray_tpu.init(num_cpus=2)
    try:
        wd = rt.control._watchdog
        assert wd is not None and wd.recompile_max == 2

        # [1] remote shape churn -> recompile counts ride the sampler.
        @ray_tpu.remote
        def churn():
            import jax
            import numpy as np
            from ray_tpu.util import device_stats as ds

            f = ds.count_compiles(jax.jit(lambda x: x + 1), "churn")
            for n in range(1, 9):
                f(np.ones(n, dtype=np.float32))
            return ds.recompiles_after_warmup().get("churn", 0)

        after = ray_tpu.get(churn.remote(), timeout=180)
        assert after > 2, after
        print(f"[1] remote shape churn: {after} post-warmup recompiles")

        rt.core.client.call({"op": "set_profile_config",
                             "enabled": True, "interval_s": 0.2})
        deadline = time.time() + 30
        while time.time() < deadline:
            prof = rt.core.client.call({"op": "get_profile"})
            hits = [s for s in prof.get("workers", {}).values()
                    if isinstance(s.get("recompiles"), dict)]
            if hits:
                break
            time.sleep(0.2)
        assert hits, "recompile counts never reached the head"
        assert all("device" in s and s["device"] is None
                   for s in prof["workers"].values())
        print(f"[2] sampler carried device fields for "
              f"{len(prof['workers'])} workers (device: null on cpu)")

        # [3] watchdog: recompile storm + injected HBM watermark.
        deadline = time.time() + 30
        while time.time() < deadline and not wd.recompile_storms_flagged:
            time.sleep(0.2)
        assert wd.recompile_storms_flagged >= 1
        rt.core.client.send({"op": "profile_report", "sample": {
            "ts": time.time(), "pid": 1, "worker": "f" * 8,
            "device": {"backend": "tpu", "watermark_fraction": 0.97}}})
        deadline = time.time() + 30
        while time.time() < deadline and not wd.hbm_alerts:
            time.sleep(0.2)
        assert wd.hbm_alerts >= 1
        events = {e["event"] for e in flight_recorder.dump()
                  if e.get("category") == "health"}
        assert {"recompile_storm", "hbm_watermark"} <= events, events
        print(f"[3] watchdog: storms={wd.recompile_storms_flagged} "
              f"hbm_alerts={wd.hbm_alerts}")

        # [4] serve engine -> continuous roofline/MFU.
        os.environ["RAY_TPU_SERVE_STEP_SAMPLE_EVERY"] = "2"
        import numpy as np
        from ray_tpu.models import transformer as tfm
        from ray_tpu.serve.llm_engine import LLMEngine

        eng = LLMEngine(tfm.TransformerConfig.tiny(), page_size=4,
                        num_pages=64, max_batch=4, multi_step=1)
        rng = np.random.default_rng(0)
        for _ in range(4):
            eng.add_request(rng.integers(1, 255, 8).tolist(),
                            max_new_tokens=8)
        while eng.has_work():
            eng.step()
        samp = eng.engine_sample
        assert samp and "roofline_fraction" in samp and "mfu" in samp
        ls = device_stats.last_step()
        assert ls and ls["plane"] == "serve"
        led = device_stats.ledger()
        assert led["components"].get("weights", 0) > 0
        print(f"[4] engine: tok/s={samp['tokens_per_s']} "
              f"roofline={samp['roofline_fraction']} mfu={samp['mfu']} "
              f"weights={led['components']['weights']}B")

        # [5] /api/device end-to-end.
        from ray_tpu.dashboard.http_head import Dashboard

        dash = Dashboard(rt)
        try:
            with urllib.request.urlopen(dash.url + "/api/device",
                                        timeout=30) as r:
                dev = json.loads(r.read())
        finally:
            dash.stop()
        assert dev["local"]["ledger"]["backend"] == "cpu"
        assert dev["watchdog"]["recompile_storms_flagged"] >= 1
        assert any(isinstance(w.get("recompiles"), dict)
                   for w in dev["workers"].values())
        print(f"[5] /api/device: backend=cpu, "
              f"{len(dev['workers'])} workers, watchdog surfaced")

        # [6] journal device stream -> opsdump lanes.
        journal.flush_all(timeout=10)
        envs = journal.replay(_journal_dir, "device")
        kinds = {e["d"]["kind"] for e in envs}
        assert "step" in kinds, kinds
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "opsdump", os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "opsdump.py"))
        opsdump = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(opsdump)
        events = opsdump.build_trace(_journal_dir, streams=("device",))
        assert any(e.get("ph") == "C" for e in events)
        print(f"[6] opsdump device lanes: {len(events)} events "
              f"from {len(envs)} journal records")
    finally:
        ray_tpu.shutdown()
    print(f"DEVICE DRIVE OK in {time.time() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
