"""Decode/serving benchmark: tokens/s through LLMEngine.step on TPU
(paged KV cache + continuous batching + device-resident multi-step).

Run: python scripts/bench_decode.py  (writes one JSON line to stdout;
results committed as DECODE_BENCH_r03.json).

The reference has no comparable in-tree number (its serve LLM tests are
pass/fail wrappers); this establishes the framework's own baseline, per
BASELINE.md 'Missing from reference'.  Two shapes run: the r02
comparison point (128+128) and a longer-generation shape (128+512).
The roofline is HONEST about both traffic terms: every decode iteration
reads the full bf16 weights AND the live KV context, so

    iters/s <= HBM_BW / (weight_bytes + avg_kv_bytes_per_iter)
    tokens/s <= iters/s * batch
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_shape(config, *, n_requests, prompt_len, max_new, page_size,
              num_pages, max_batch, multi_step, hbm_gb_s):
    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine

    eng = LLMEngine(config, page_size=page_size, num_pages=num_pages,
                    max_batch=max_batch, multi_step=multi_step)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    # Warmup compiles every bucket the measured run hits: the batched
    # prefill and one decode program per pow-2 context-width bucket
    # (steady-state serving never pays compiles, so neither should the
    # measurement).
    warm = [rng.integers(1, config.vocab_size, prompt_len).tolist()
            for _ in range(max_batch)]
    eng.generate(warm, max_new_tokens=max_new)

    t0 = time.perf_counter()
    ids = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
    results = {}
    steps = 0
    while eng.has_work():
        results.update(eng.step())
        steps += 1
    dt = time.perf_counter() - t0
    assert set(ids) <= set(results), "missing results"
    gen_tokens = sum(len(results[i]) for i in ids)

    weight_bytes = 2 * tfm.num_params(config)
    # Average KV bytes read per decode iteration: bf16 K+V over the
    # average live context across the generation window.
    kv_per_token = (2 * config.num_layers * config.num_kv_heads
                    * config.head_dim_ * 2)
    avg_ctx = prompt_len + max_new / 2
    kv_bytes = max_batch * avg_ctx * kv_per_token
    roofline_tok_s = hbm_gb_s / (weight_bytes + kv_bytes) * max_batch
    tok_s = gen_tokens / dt
    return {
        "tokens_per_sec": round(tok_s, 1),
        "roofline_tokens_per_sec": round(roofline_tok_s, 1),
        "roofline_fraction": round(tok_s / roofline_tok_s, 3),
        "generated_tokens": gen_tokens,
        "prefill_tokens": n_requests * prompt_len,
        "wall_s": round(dt, 2),
        "engine_steps": steps,
        "concurrent_requests": n_requests,
        "max_batch": max_batch,
        "multi_step": multi_step,
        "seq": f"{prompt_len}+{max_new}",
    }


def main():
    import jax

    from ray_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    hbm_gb_s = {"TPU v5 lite": 819e9, "TPU v5": 2765e9,
                "TPU v4": 1228e9}.get(
        getattr(devices[0], "device_kind", ""), 819e9)
    if on_tpu:
        # Inference-sized 1.1B (no optimizer state): bf16 weights + a
        # ~4 GB paged KV pool fit comfortably in 16 GB HBM.
        # 1.0B GQA 4:1 (TinyLlama-class): grouped-query attention is
        # the TPU-first shape — 4x the MXU work per KV byte streamed,
        # 4x smaller KV pool, so batch (and the bandwidth roofline's
        # useful output) doubles.
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=16, num_kv_heads=4,
            max_seq_len=2048, remat=False)
        # multi_step = max_new: the whole generation runs device-resident
        # in one dispatch per wave (greedy bench has no per-token host
        # decisions; latency-sensitive serving would use a smaller burst).
        # The GQA KV pool covers batch 128 x 256-token contexts (2048 of
        # 4096 pages) for the short shape.
        shapes = [
            dict(n_requests=128, prompt_len=128, max_new=128,
                 page_size=16, num_pages=4096, max_batch=128,
                 multi_step=128),
            dict(n_requests=64, prompt_len=128, max_new=512,
                 page_size=16, num_pages=4096, max_batch=64,
                 multi_step=512),
        ]
    else:
        config = tfm.TransformerConfig.tiny()
        shapes = [dict(n_requests=4, prompt_len=8, max_new=8,
                       page_size=4, num_pages=64, max_batch=4,
                       multi_step=1)]

    rows = [run_shape(config, hbm_gb_s=hbm_gb_s, **s) for s in shapes]
    head = rows[0]
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "tokens/s",
        "roofline_tokens_per_sec": head["roofline_tokens_per_sec"],
        "roofline_fraction": head["roofline_fraction"],
        "roofline_note": ("HBM_BW / (weight_bytes + avg live KV bytes) "
                          "x batch — both traffic terms every decode "
                          "iteration reads; wall includes prefill and "
                          "per-dispatch transport latency on the "
                          "tunneled dev chip"),
        "shapes": rows,
        "model_params": tfm.num_params(config),
        "device": getattr(devices[0], "device_kind", devices[0].platform),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
