"""Decode/serving benchmark: tokens/s through LLMEngine.step on TPU
(paged KV cache + continuous batching + chunked multi-step decode).

Run: python scripts/bench_decode.py  (writes one JSON line to stdout;
results committed as DECODE_BENCH_r04.json).

The reference has no comparable in-tree number (its serve LLM tests are
pass/fail wrappers); this establishes the framework's own baseline, per
BASELINE.md 'Missing from reference'.  Two shapes run: the r02
comparison point (128+128) and a longer-generation shape (128+512).

Honesty rules:
  - decode-only throughput excludes engine steps that performed any
    admission/prefill work; the headline roofline fraction is computed
    against the DECODE-ONLY rate (the whole-run rate is also reported).
  - the roofline counts both traffic terms every decode iteration
    reads: full bf16 weights AND the average live KV context:
        iters/s <= HBM_BW / (weight_bytes + avg_kv_bytes_per_iter)
        tokens/s <= iters/s * batch
  - dispatch is CHUNKED (multi_step=32), not one wave-sized dispatch:
    queued requests join the batch at every chunk boundary (<= 32
    tokens of wait), which is what the continuous-batching claim
    requires; tests/test_llm_decoding.py::test_mid_generation_admission
    pins the behavior.
  - per-request latency is recorded: TTFT (add_request -> first token
    available on the host) and TPOT ((last - first)/(n-1)); p50/p99
    across requests.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs), q))


def run_shape(config, *, n_requests, prompt_len, max_new, page_size,
              num_pages, max_batch, multi_step, hbm_gb_s):
    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine

    eng = LLMEngine(config, page_size=page_size, num_pages=num_pages,
                    max_batch=max_batch, multi_step=multi_step)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    # Warmup compiles every program the measured run hits: the packed
    # admission wave, one decode program per pow-2 context-width
    # bucket, AND the dirty-slot merge (a mid-run admission while old
    # slots finish exercises merge_slot_state; steady-state serving
    # never pays compiles, so neither should the measurement).
    warm = [rng.integers(1, config.vocab_size, prompt_len).tolist()
            for _ in range(max_batch)]
    eng.generate(warm, max_new_tokens=max_new)
    stagger = [rng.integers(1, config.vocab_size, prompt_len).tolist()
               for _ in range(2)]
    eng.add_request(stagger[0], max_new_tokens=max_new)
    eng.step()
    eng.add_request(stagger[1], max_new_tokens=8)
    while eng.has_work():
        eng.step()

    t0 = time.perf_counter()
    t_add = {}
    ids = []
    for p in prompts:
        rid = eng.add_request(p, max_new_tokens=max_new)
        t_add[rid] = time.perf_counter()
        ids.append(rid)
    results = {}
    t_first = {}
    t_done = {}
    steps = 0
    decode_wall = 0.0
    decode_tokens = 0
    emitted_prev = 0

    def emitted_now():
        live = sum(len(r.generated) for r in eng.slot_req if r is not None)
        done = sum(len(v) for v in results.values())
        return live + done

    while eng.has_work():
        waiting_before = len(eng.waiting)
        waves_before = (eng.waves_dispatched, eng.prefill_reconciles)
        ts = time.perf_counter()
        done = eng.step()
        te = time.perf_counter()
        steps += 1
        results.update(done)
        now = te
        for rid, toks in done.items():
            t_done[rid] = now
        for r in eng.slot_req:
            if r is not None and r.generated and r.req_id not in t_first:
                t_first[r.req_id] = now
        for rid in done:
            t_first.setdefault(rid, now)
        emitted = emitted_now()
        if (len(eng.waiting) == waiting_before and waiting_before == 0
                and (eng.waves_dispatched,
                     eng.prefill_reconciles) == waves_before):
            # Pure decode step: no admission/prefill work happened —
            # dispatching a wave or waiting on a wave's first tokens
            # both disqualify the step from the decode-only wall.
            decode_wall += te - ts
            decode_tokens += emitted - emitted_prev
        emitted_prev = emitted
    dt = time.perf_counter() - t0
    assert set(ids) <= set(results), "missing results"
    gen_tokens = sum(len(results[i]) for i in ids)

    weight_bytes = 2 * tfm.num_params(config)
    # Average KV bytes read per decode iteration: bf16 K+V over the
    # average live context across the generation window.
    kv_per_token = (2 * config.num_layers * config.num_kv_heads
                    * config.head_dim_ * 2)
    avg_ctx = prompt_len + max_new / 2
    kv_bytes = max_batch * avg_ctx * kv_per_token
    roofline_tok_s = hbm_gb_s / (weight_bytes + kv_bytes) * max_batch
    tok_s = gen_tokens / dt
    decode_tok_s = decode_tokens / decode_wall if decode_wall else 0.0
    ttft = [t_first[i] - t_add[i] for i in ids]
    tpot = [(t_done[i] - t_first[i]) / (len(results[i]) - 1)
            for i in ids if len(results[i]) > 1]
    return {
        "decode_only_tokens_per_sec": round(decode_tok_s, 1),
        "decode_only_roofline_fraction": round(
            decode_tok_s / roofline_tok_s, 3),
        "tokens_per_sec": round(tok_s, 1),
        "roofline_tokens_per_sec": round(roofline_tok_s, 1),
        "roofline_fraction": round(tok_s / roofline_tok_s, 3),
        "ttft_p50_s": round(_pct(ttft, 50), 4),
        "ttft_p99_s": round(_pct(ttft, 99), 4),
        "tpot_p50_ms": round(_pct(tpot, 50) * 1e3, 3),
        "tpot_p99_ms": round(_pct(tpot, 99) * 1e3, 3),
        "generated_tokens": gen_tokens,
        "decode_only_tokens": decode_tokens,
        "decode_only_wall_s": round(decode_wall, 2),
        "prefill_tokens": n_requests * prompt_len,
        "wall_s": round(dt, 2),
        "engine_steps": steps,
        "concurrent_requests": n_requests,
        "max_batch": max_batch,
        "multi_step": multi_step,
        "page_size": page_size,
        "seq": f"{prompt_len}+{max_new}",
    }


def main():
    import jax

    from ray_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    hbm_gb_s = {"TPU v5 lite": 819e9, "TPU v5": 2765e9,
                "TPU v4": 1228e9}.get(
        getattr(devices[0], "device_kind", ""), 819e9)
    if on_tpu:
        # 1.0B GQA 4:1 (TinyLlama-class): grouped-query attention is
        # the TPU-first shape — 4x the MXU work per KV byte streamed,
        # 4x smaller KV pool, so batch (and the bandwidth roofline's
        # useful output) doubles.  page_size=128: the decode kernel
        # streams one fused-head page per DMA (ops/paged_attention.py),
        # so pages must be big enough that DMAs amortize issue latency.
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=16, num_kv_heads=4,
            max_seq_len=2048, remat=False)
        # multi_step=32: chunked dispatch — a whole-generation dispatch
        # would maximize throughput but lock queued requests out for
        # the entire wave; 32 bounds the admission wait while keeping
        # host sync overhead ~3% (one sync per 32 device iterations).
        # page_size=128 measured best on both shapes (bigger DMAs for
        # the decode kernel AND far fewer pages for prefill's scatter
        # bookkeeping: whole-run +36% over page=64 at 128+128).
        shapes = [
            dict(n_requests=128, prompt_len=128, max_new=128,
                 page_size=128, num_pages=320, max_batch=128,
                 multi_step=32),
            dict(n_requests=64, prompt_len=128, max_new=512,
                 page_size=128, num_pages=384, max_batch=64,
                 multi_step=32),
        ]
    else:
        config = tfm.TransformerConfig.tiny()
        shapes = [dict(n_requests=4, prompt_len=8, max_new=8,
                       page_size=4, num_pages=64, max_batch=4,
                       multi_step=1)]

    rows = [run_shape(config, hbm_gb_s=hbm_gb_s, **s) for s in shapes]
    head = rows[0]
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": head["decode_only_tokens_per_sec"],
        "unit": "tokens/s",
        "roofline_tokens_per_sec": head["roofline_tokens_per_sec"],
        "roofline_fraction": head["decode_only_roofline_fraction"],
        "roofline_note": ("decode-only rate vs HBM_BW / (weight_bytes "
                          "+ avg live KV bytes) x batch — both traffic "
                          "terms every decode iteration reads; steps "
                          "that did admission/prefill are excluded "
                          "from the decode-only wall; whole-run rate "
                          "(incl. prefill + tunnel dispatch latency) "
                          "reported per shape"),
        "shapes": rows,
        "model_params": tfm.num_params(config),
        "device": getattr(devices[0], "device_kind", devices[0].platform),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
