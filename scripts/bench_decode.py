"""Decode/serving benchmark: tokens/s through LLMEngine.step on TPU
(paged KV cache + continuous batching + optional prompt-lookup
speculation).

Run: python scripts/bench_decode.py  (writes one JSON line to stdout;
results committed as DECODE_BENCH_r02.json).

The reference has no comparable in-tree number (its serve LLM tests are
pass/fail wrappers); this establishes the framework's own baseline, per
BASELINE.md 'Missing from reference'.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import jax

    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    if on_tpu:
        # Inference-sized 1.1B (no optimizer state): bf16 weights + a
        # ~2 GB paged KV pool fit comfortably in 16 GB HBM.  multi_step
        # 32 amortizes the per-dispatch transport latency (~35 ms on
        # the tunneled dev chip; measured ~3.5 ms/iteration device
        # time at batch 16 = 77% of the weights-bandwidth roofline).
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=8192,
            num_layers=16, num_heads=16, num_kv_heads=16,
            max_seq_len=2048, remat=False)
        n_requests, prompt_len, max_new = 64, 128, 128
        page_size, num_pages, max_batch = 16, 1024, 32
        multi_step = 32
    else:
        multi_step = 1
    if not on_tpu:
        config = tfm.TransformerConfig.tiny()
        n_requests, prompt_len, max_new = 4, 8, 8
        page_size, num_pages, max_batch = 4, 64, 4

    eng = LLMEngine(config, page_size=page_size, num_pages=num_pages,
                    max_batch=max_batch, multi_step=multi_step)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    # Warmup: compile every bucket the measured run will hit — the full
    # batched-prefill (B=max_batch, S bucket of prompt_len) and the
    # decode/multi-step programs.  Compiles are cached; steady-state
    # serving never pays them, so neither should the measurement.
    warm = [rng.integers(1, config.vocab_size, prompt_len).tolist()
            for _ in range(max_batch)]
    eng.generate(warm, max_new_tokens=multi_step + 1)

    t0 = time.perf_counter()
    ids = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
    results = {}
    steps = 0
    while eng.has_work():
        results.update(eng.step())
        steps += 1
    dt = time.perf_counter() - t0
    assert set(ids) <= set(results), "missing results"
    # Engine results are the GENERATED tokens (prompt excluded).
    gen_tokens = sum(len(results[i]) for i in ids)
    prefill_tokens = n_requests * prompt_len

    # Weights-bandwidth roofline: every decode iteration reads the full
    # bf16 weights once; HBM bandwidth caps iterations/s, and batch
    # multiplies tokens per iteration (VERDICT r2 framing).
    hbm_gb_s = {"TPU v5 lite": 819e9, "TPU v5": 2765e9,
                "TPU v4": 1228e9}.get(
        getattr(devices[0], "device_kind", ""), 819e9)
    weight_bytes = 2 * tfm.num_params(config)
    roofline_tok_s = hbm_gb_s / weight_bytes * max_batch
    tok_s = gen_tokens / dt
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "roofline_tokens_per_sec": round(roofline_tok_s, 1),
        "roofline_fraction": round(tok_s / roofline_tok_s, 3),
        "roofline_note": ("weights-bandwidth bound: HBM_BW / "
                          "(2 B/param) x batch; includes prefill + "
                          "per-dispatch transport latency in the wall"),
        "generated_tokens": gen_tokens,
        "prefill_tokens": prefill_tokens,
        "wall_s": round(dt, 2),
        "engine_steps": steps,
        "concurrent_requests": n_requests,
        "max_batch": max_batch,
        "multi_step": multi_step,
        "model_params": tfm.num_params(config),
        "seq": f"{prompt_len}+{max_new}",
        "device": getattr(devices[0], "device_kind", devices[0].platform),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
