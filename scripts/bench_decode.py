"""Decode/serving benchmark: tokens/s through LLMEngine.step on TPU
(paged KV cache + continuous batching + optional prompt-lookup
speculation).

Run: python scripts/bench_decode.py  (writes one JSON line to stdout;
results committed as DECODE_BENCH_r02.json).

The reference has no comparable in-tree number (its serve LLM tests are
pass/fail wrappers); this establishes the framework's own baseline, per
BASELINE.md 'Missing from reference'.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import jax

    from ray_tpu.models import transformer as tfm
    from ray_tpu.serve.llm_engine import LLMEngine

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    if on_tpu:
        # Inference-sized 1.1B (no optimizer state): bf16 weights + a
        # ~1 GB paged KV pool fit comfortably in 16 GB HBM.
        config = tfm.TransformerConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=8192,
            num_layers=16, num_heads=16, num_kv_heads=16,
            max_seq_len=2048, remat=False)
        n_requests, prompt_len, max_new = 32, 128, 128
        page_size, num_pages, max_batch = 16, 512, 16
        multi_step = 8
    else:
        multi_step = 1
    if not on_tpu:
        config = tfm.TransformerConfig.tiny()
        n_requests, prompt_len, max_new = 4, 8, 8
        page_size, num_pages, max_batch = 4, 64, 4

    eng = LLMEngine(config, page_size=page_size, num_pages=num_pages,
                    max_batch=max_batch, multi_step=multi_step)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    # Warmup: compile every bucket the measured run will hit — the full
    # batched-prefill (B=max_batch, S bucket of prompt_len) and the
    # decode/multi-step programs.  Compiles are cached; steady-state
    # serving never pays them, so neither should the measurement.
    warm = [rng.integers(1, config.vocab_size, prompt_len).tolist()
            for _ in range(max_batch)]
    eng.generate(warm, max_new_tokens=multi_step + 1)

    t0 = time.perf_counter()
    ids = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
    results = {}
    steps = 0
    while eng.has_work():
        results.update(eng.step())
        steps += 1
    dt = time.perf_counter() - t0
    assert set(ids) <= set(results), "missing results"
    # Engine results are the GENERATED tokens (prompt excluded).
    gen_tokens = sum(len(results[i]) for i in ids)
    prefill_tokens = n_requests * prompt_len

    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(gen_tokens / dt, 1),
        "unit": "tokens/s",
        "generated_tokens": gen_tokens,
        "prefill_tokens": prefill_tokens,
        "wall_s": round(dt, 2),
        "engine_steps": steps,
        "concurrent_requests": n_requests,
        "max_batch": max_batch,
        "model_params": tfm.num_params(config),
        "seq": f"{prompt_len}+{max_new}",
        "device": getattr(devices[0], "device_kind", devices[0].platform),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
