"""Isolated flash-attention kernel timings at the headline bench shapes.

Prints fwd and fwd+bwd wall times and achieved FLOP/s vs the chip peak,
for a grid of (block_q, block_k) — locates how much of the train step's
non-MXU time lives in the attention kernels and which tiling recovers it.

Usage: python scripts/bench_attention.py [b] [s] [h] [d]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from bench import _peak_flops
    from ray_tpu.ops.attention import flash_attention

    b = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    s = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    h = int(sys.argv[3]) if len(sys.argv) > 3 else 14
    d = int(sys.argv[4]) if len(sys.argv) > 4 else 128

    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)
    k = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)
    v = jax.random.normal(key, (b, s, h, d), dtype=jnp.bfloat16)

    peak = _peak_flops(jax.devices()[0])
    # causal attention FLOPs: 2 matmuls (QK^T, PV) over the lower
    # triangle = 2 * 2 * b*h*s^2*d / 2
    fwd_flops = 2 * b * h * s * s * d
    steps = 20

    for bq, bk in ((128, 128), (256, 256), (256, 512), (512, 512),
                   (128, 512), (512, 1024)):
        if bq > s or bk > s:
            continue

        def fwd(q, k, v, bq=bq, bk=bk):
            return flash_attention(q, k, v, causal=True,
                                   block_q=bq, block_k=bk)

        jfwd = jax.jit(fwd)
        out = jfwd(q, k, v)
        float(out.sum())  # sync
        t0 = time.perf_counter()
        for _ in range(steps):
            out = jfwd(q, k, v)
        float(out.sum())
        dt = (time.perf_counter() - t0) / steps
        eff_f = fwd_flops / dt / peak

        def loss(q, k, v, bq=bq, bk=bk):
            return flash_attention(q, k, v, causal=True, block_q=bq,
                                   block_k=bk).astype(jnp.float32).sum()

        jgrad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        g = jgrad(q, k, v)
        float(g[0].sum())
        t0 = time.perf_counter()
        for _ in range(steps):
            g = jgrad(q, k, v)
        float(g[0].sum())
        dtg = (time.perf_counter() - t0) / steps
        # fwd+bwd ~ 3.5x fwd matmul work (dq, dk, dv + p recompute x2)
        eff_g = 3.5 * fwd_flops / dtg / peak
        print(f"bq={bq:<4d} bk={bk:<4d}: fwd {dt*1e3:7.2f} ms "
              f"({eff_f*100:5.1f}% peak)   fwd+bwd {dtg*1e3:7.2f} ms "
              f"({eff_g*100:5.1f}% of peak at 3.5x-fwd credit)",
              flush=True)


if __name__ == "__main__":
    main()
